"""Apollo config datasource: the notifications/v2 long-poll protocol
(reference: ``sentinel-datasource-apollo``'s ``ApolloDataSource`` — the
Apollo client's ``ConfigChangeListener`` on one property key inside a
namespace, here spoken directly over Apollo's meta/config-service HTTP
wire — SURVEY.md §2.2).

The three real endpoints (no Apollo SDK):

- ``GET /notifications/v2?appId=&cluster=&notifications=[{"namespaceName":
  ..., "notificationId": ...}]`` — the server parks the request until the
  namespace's notification id advances past the submitted one (or ~60s),
  then answers 200 with the new ids; 304 = nothing changed, poll again.
- ``GET /configs/{appId}/{cluster}/{namespace}?releaseKey=`` — the full
  released key→value map as JSON; 304 when ``releaseKey`` still matches
  (the client echoes the last seen release, exactly like the real one).
- the open-api item+release pair (``POST/PUT …/items/…`` then
  ``POST …/releases``) — the writable side, mirroring the reference
  dashboard's ``ApolloOpenApiClient`` publisher: rule edits land in the
  namespace's working copy and become visible only on release, which is
  Apollo's actual durability model.

Like the reference, the datasource reads ONE property key (e.g.
``flowRules``) out of the namespace; other keys in the same namespace are
ignored. Delivery is at-least-once across outages: the notification id
comparison on reconnect answers immediately if anything was missed, and
the releaseKey echo suppresses no-op re-reads. Bad payloads keep the
last good rules.

``MiniApolloServer`` is the in-repo fake (the endpoints above with real
long-poll parking and working-copy/release separation); point the
datasource at a real Apollo config service and no line changes.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from http.server import BaseHTTPRequestHandler
from typing import Dict, Optional, Tuple

from sentinel_tpu.datasource._mini_http import (
    RestartableHTTPServer,
    normalize_base,
)
from sentinel_tpu.datasource.base import (
    AbstractDataSource,
    Converter,
    ReconnectingWatchMixin,
    T,
    WritableDataSource,
    _log_warn,
)

NOTIFICATION_INIT = -1  # Apollo: "never seen any release" sentinel


class ApolloDataSource(ReconnectingWatchMixin, AbstractDataSource[str, T]):
    """Initial config GET + notifications/v2 long-poll, reconnect/backoff.

    ``poll_timeout_ms`` bounds one long-poll round client-side (Apollo
    servers hold ~60s; tests shrink it via the fake's ``max_hold_ms``).
    """

    _watch_exceptions = (OSError, urllib.error.URLError, ValueError)
    _watch_thread_name = "sentinel-apollo-listener"

    def __init__(self, server_addr: str, app_id: str, namespace: str,
                 rule_key: str, converter: Converter,
                 cluster: str = "default", poll_timeout_ms: int = 60000,
                 reconnect_backoff_ms: Tuple[int, int] = (50, 2000)):
        super().__init__(converter)
        self.base = normalize_base(server_addr)
        self.app_id, self.cluster = app_id, cluster
        self.namespace, self.rule_key = namespace, rule_key
        self.poll_timeout_ms = poll_timeout_ms
        self._notification_id = NOTIFICATION_INIT
        self._release_key = ""
        self._init_watch(reconnect_backoff_ms)

    # -- ReadableDataSource ------------------------------------------------

    def read_source(self) -> Optional[str]:
        """The rule key's current released value (None if absent)."""
        cfg = self._fetch_config(release_key="")
        if cfg is None:
            return None
        return cfg.get("configurations", {}).get(self.rule_key)

    def start(self) -> "ApolloDataSource":
        try:
            self._apply_config(self._fetch_config(release_key=""))
        except (OSError, urllib.error.URLError) as ex:
            _log_warn("apollo datasource initial load failed: %r", ex)
        self._start_watching()
        return self

    def close(self) -> None:
        self._join_watch()

    # -- internals ---------------------------------------------------------

    def _fetch_config(self, release_key: Optional[str] = None
                      ) -> Optional[dict]:
        """``GET /configs/...``; None on 404 (namespace never released)
        or 304 (releaseKey unchanged)."""
        if release_key is None:
            release_key = self._release_key
        qs = urllib.parse.urlencode({"releaseKey": release_key})
        url = (f"{self.base}/configs/{urllib.parse.quote(self.app_id)}/"
               f"{urllib.parse.quote(self.cluster)}/"
               f"{urllib.parse.quote(self.namespace)}?{qs}")
        try:
            with urllib.request.urlopen(url, timeout=5.0) as resp:
                return json.loads(resp.read().decode("utf-8"))
        except urllib.error.HTTPError as ex:
            if ex.code in (304, 404):
                return None
            raise

    def _apply_config(self, cfg: Optional[dict]) -> None:
        if cfg is None or self._stop.is_set():
            # stop guard: a straggler round completing after close() must
            # not mutate rules under a caller that shut the source down
            return
        # releaseKey advances on RECEIPT, applied or not (the Apollo
        # client's bookkeeping) — advancing only on successful conversion
        # would busy-loop the config fetch on a bad payload.
        self._release_key = cfg.get("releaseKey", "")
        raw = cfg.get("configurations", {}).get(self.rule_key)
        if raw is None:
            return  # rule key absent in this release: keep last good
        try:
            value = self.converter(raw)
        except Exception as ex:  # keep last good rules
            _log_warn("apollo datasource bad payload: %r", ex)
            return
        if value is not None:
            self._property.update_value(value)

    def _watch_round(self) -> None:
        """One notifications/v2 round: park, then fetch on change."""
        notifications = json.dumps([{
            "namespaceName": self.namespace,
            "notificationId": self._notification_id}])
        qs = urllib.parse.urlencode({
            "appId": self.app_id, "cluster": self.cluster,
            "notifications": notifications})
        try:
            with urllib.request.urlopen(
                    f"{self.base}/notifications/v2?{qs}",
                    timeout=self.poll_timeout_ms / 1000.0 + 10.0) as resp:
                changed = json.loads(resp.read().decode("utf-8"))
        except urllib.error.HTTPError as ex:
            if ex.code == 304:  # quiet round: the server is fine
                self._healthy()
                return
            raise
        for note in changed:
            if note.get("namespaceName") != self.namespace:
                continue
            # Fetch BEFORE advancing the id: if the config GET fails here
            # (server blip right after the notify), the mixin reconnects
            # and the next poll re-submits the OLD id, so the server
            # re-answers immediately and the release is re-delivered —
            # advancing first would mark it seen and silently skip it
            # until some future release (breaking at-least-once).
            self._apply_config(self._fetch_config())
            self._notification_id = note.get("notificationId",
                                             self._notification_id)
        self._healthy()


class ApolloWritableDataSource(WritableDataSource[T]):
    """Open-api item upsert + release (the reference dashboard publisher's
    ``ApolloOpenApiClient`` two-step: a written item is invisible until
    released)."""

    def __init__(self, server_addr: str, app_id: str, namespace: str,
                 rule_key: str, encoder: Converter, cluster: str = "default",
                 env: str = "DEV", token: str = ""):
        self.base = normalize_base(server_addr)
        self.app_id, self.cluster = app_id, cluster
        self.namespace, self.rule_key = namespace, rule_key
        self.encoder = encoder
        self.env, self.token = env, token

    def _open_api(self, tail: str) -> str:
        return (f"{self.base}/openapi/v1/envs/{urllib.parse.quote(self.env)}"
                f"/apps/{urllib.parse.quote(self.app_id)}"
                f"/clusters/{urllib.parse.quote(self.cluster)}"
                f"/namespaces/{urllib.parse.quote(self.namespace)}{tail}")

    def _call(self, method: str, url: str, payload: dict) -> int:
        req = urllib.request.Request(
            url, data=json.dumps(payload).encode("utf-8"), method=method,
            headers={"Content-Type": "application/json;charset=UTF-8",
                     "Authorization": self.token})
        try:
            with urllib.request.urlopen(req, timeout=5.0) as resp:
                return resp.status
        except urllib.error.HTTPError as ex:
            return ex.code

    def write(self, value: T) -> None:
        item = {"key": self.rule_key, "value": self.encoder(value),
                "dataChangeCreatedBy": "sentinel"}
        # PUT updates an existing item; 404 = first write, POST creates.
        code = self._call(
            "PUT", self._open_api(f"/items/{urllib.parse.quote(self.rule_key)}"
                                  "?createIfNotExists=false"), item)
        if code == 404:
            code = self._call("POST", self._open_api("/items"), item)
        if code not in (200, 201):
            raise OSError(f"apollo item write rejected ({code})")
        code = self._call("POST", self._open_api("/releases"), {
            "releaseTitle": "sentinel-rule-push",
            "releasedBy": "sentinel"})
        if code not in (200, 201):
            raise OSError(f"apollo release rejected ({code})")


# -- in-repo fake server ------------------------------------------------------


class _ApolloHandler(BaseHTTPRequestHandler):
    def _send(self, code: int, body: bytes = b"",
              ctype: str = "application/json;charset=UTF-8") -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _json(self, code: int, obj) -> None:
        self._send(code, json.dumps(obj).encode("utf-8"))

    def do_GET(self):  # noqa: N802 — http.server API
        server: "MiniApolloServer" = self.server  # type: ignore
        path, _, query = self.path.partition("?")
        q = urllib.parse.parse_qs(query)
        parts = [urllib.parse.unquote(p) for p in path.split("/") if p]
        if parts[:1] == ["notifications"] and parts[1:2] == ["v2"]:
            return self._long_poll(server, q)
        if parts[:1] == ["configs"] and len(parts) == 4:
            _, app_id, cluster, namespace = parts
            key = (app_id, cluster, namespace)
            with server._cond:
                ns = server._released.get(key)
            if ns is None:
                return self._json(404, {"message": "namespace not found"})
            release_key, configurations = ns
            if q.get("releaseKey", [""])[0] == release_key:
                return self._send(304)
            return self._json(200, {
                "appId": app_id, "cluster": cluster,
                "namespaceName": namespace,
                "configurations": configurations,
                "releaseKey": release_key})
        self._json(404, {"message": "not found"})

    def _long_poll(self, server: "MiniApolloServer", q) -> None:
        app_id = q.get("appId", [""])[0]
        cluster = q.get("cluster", ["default"])[0]
        try:
            wanted = json.loads(q.get("notifications", ["[]"])[0])
        except ValueError:
            return self._json(400, {"message": "bad notifications"})
        deadline = time.monotonic() + server.max_hold_ms / 1000.0

        def changed():
            out = []
            for note in wanted:
                ns = note.get("namespaceName", "")
                seen = note.get("notificationId", NOTIFICATION_INIT)
                cur = server._notifications.get((app_id, cluster, ns), 0)
                if cur > seen:
                    out.append({"namespaceName": ns, "notificationId": cur})
            return out

        with server._cond:
            server.poll_rounds += 1
            while True:
                hits = changed()
                remaining = deadline - time.monotonic()
                if hits or remaining <= 0 or server._stopping:
                    break
                server._cond.wait(min(remaining, 0.25))
        if hits:
            return self._json(200, hits)
        self._send(304)

    def do_POST(self):  # noqa: N802 — http.server API
        self._open_api_write(create=True)

    def do_PUT(self):  # noqa: N802 — http.server API
        self._open_api_write(create=False)

    def _open_api_write(self, create: bool) -> None:
        server: "MiniApolloServer" = self.server  # type: ignore
        parts = [urllib.parse.unquote(p)
                 for p in self.path.partition("?")[0].split("/") if p]
        # /openapi/v1/envs/{env}/apps/{app}/clusters/{c}/namespaces/{ns}/…
        if parts[:2] != ["openapi", "v1"] or len(parts) < 10:
            return self._json(404, {"message": "not found"})
        app_id, cluster, namespace = parts[5], parts[7], parts[9]
        key = (app_id, cluster, namespace)
        n = int(self.headers.get("Content-Length", "0"))
        try:
            payload = json.loads(self.rfile.read(n).decode("utf-8") or "{}")
        except ValueError:
            return self._json(400, {"message": "bad json"})
        if server.token and \
                self.headers.get("Authorization", "") != server.token:
            return self._json(401, {"message": "unauthorized"})
        tail = parts[10:]
        if tail[:1] == ["items"]:
            item_key = tail[1] if len(tail) > 1 else payload.get("key", "")
            with server._cond:
                items = server._working.setdefault(key, {})
                if not create and item_key not in items:
                    return self._json(404, {"message": "item not found"})
                items[item_key or payload.get("key", "")] = \
                    payload.get("value", "")
            return self._json(200, payload)
        if tail[:1] == ["releases"]:
            server.release(app_id, cluster, namespace)
            return self._json(200, {"releaseTitle":
                                    payload.get("releaseTitle", "")})
        self._json(404, {"message": "not found"})

    def log_message(self, fmt, *args):  # quiet
        pass


class MiniApolloServer(RestartableHTTPServer):
    """Apollo config-service + open-api subset with real long-poll parking
    and working-copy/release separation. ``stop()``/``start()`` rebinds
    the same port; released configs and notification ids survive (a real
    Apollo's would too). ``max_hold_ms`` caps listener parking for tests.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 max_hold_ms: int = 60000, token: str = ""):
        super().__init__(host, port, _ApolloHandler)
        self.max_hold_ms = max_hold_ms
        self.token = token
        # (appId, cluster, ns) -> (releaseKey, {key: value})  [released]
        self._released: Dict[Tuple[str, str, str],
                             Tuple[str, Dict[str, str]]] = {}
        # (appId, cluster, ns) -> {key: value}                [unreleased]
        self._working: Dict[Tuple[str, str, str], Dict[str, str]] = {}
        self._notifications: Dict[Tuple[str, str, str], int] = {}
        self._release_seq = 0

    def publish(self, app_id: str, namespace: str, key: str, value: str,
                cluster: str = "default") -> None:
        """Write + release in one step (as the open-api two-step would)."""
        k = (app_id, cluster, namespace)
        with self._cond:
            self._working.setdefault(k, {})[key] = value
        self.release(app_id, cluster, namespace)

    def release(self, app_id: str, cluster: str, namespace: str) -> None:
        k = (app_id, cluster, namespace)
        with self._cond:
            self._release_seq += 1
            working = dict(self._working.get(k, {}))
            self._released[k] = (f"release-{self._release_seq}", working)
            self._notifications[k] = self._release_seq
            self._cond.notify_all()

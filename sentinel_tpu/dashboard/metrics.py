"""Dashboard metrics pipeline: poller + in-memory repository.

Reference: ``dashboard:metric/MetricFetcher.java`` (polls every healthy
machine's ``/metric`` on a ~1s cadence over a lagged window, parses
``MetricNode`` thin lines) + ``dashboard:repository/metric/
InMemoryMetricsRepository.java`` (per (app, resource) time-series, 5-minute
retention, queried by the UI).
"""

from __future__ import annotations

import threading
import time
from collections import defaultdict
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from sentinel_tpu.dashboard.client import ApiError, SentinelApiClient
from sentinel_tpu.dashboard.discovery import AppManagement
from sentinel_tpu.metrics.metric_node import MetricNode

RETENTION_MS = 5 * 60_000   # reference: 5-minute in-memory retention
FETCH_LAG_MS = 2_000        # read sealed seconds only (reference lags ~6s)
FETCH_SPAN_MS = 6_000       # window length per poll


@dataclass
class MetricEntry:
    """One (app, resource, second) aggregated across machines."""

    timestamp: int
    pass_qps: int = 0
    block_qps: int = 0
    success_qps: int = 0
    exception_qps: int = 0
    rt_sum: float = 0.0       # sum of per-machine avg RT (weight = machines)
    machines: int = 0

    @property
    def avg_rt(self) -> float:
        return self.rt_sum / self.machines if self.machines else 0.0

    def to_dict(self, resource: str) -> Dict:
        return {
            "resource": resource, "timestamp": self.timestamp,
            "passQps": self.pass_qps, "blockQps": self.block_qps,
            "successQps": self.success_qps, "exceptionQps": self.exception_qps,
            "rt": round(self.avg_rt, 2),
        }


class InMemoryMetricsRepository:
    """(app, resource) -> {second_ts -> MetricEntry}, TTL-evicted."""

    def __init__(self, retention_ms: int = RETENTION_MS):
        self.retention_ms = retention_ms
        self._lock = threading.Lock()
        self._data: Dict[Tuple[str, str], Dict[int, MetricEntry]] = defaultdict(dict)

    def save(self, app: str, node: MetricNode) -> None:
        with self._lock:
            series = self._data[(app, node.resource)]
            e = series.get(node.timestamp)
            if e is None:
                e = series[node.timestamp] = MetricEntry(timestamp=node.timestamp)
            e.pass_qps += node.pass_qps
            e.block_qps += node.block_qps
            e.success_qps += node.success_qps
            e.exception_qps += node.exception_qps
            e.rt_sum += node.rt
            e.machines += 1

    def _evict(self, now_ms: int) -> None:
        floor = now_ms - self.retention_ms
        with self._lock:
            for key in list(self._data):
                series = self._data[key]
                for ts in [t for t in series if t < floor]:
                    del series[ts]
                if not series:
                    del self._data[key]

    def apps(self) -> List[str]:
        """Apps with any retained series (OpenMetrics export iterates
        this, not discovery — aggregates can outlive a machine's
        heartbeat within the retention window)."""
        with self._lock:
            return sorted({a for (a, _r) in self._data})

    def resources_of(self, app: str) -> List[str]:
        with self._lock:
            return sorted({r for (a, r) in self._data if a == app})

    def query(self, app: str, resource: str,
              start_ms: int, end_ms: int) -> List[Dict]:
        with self._lock:
            series = dict(self._data.get((app, resource), {}))
        return [e.to_dict(resource) for ts, e in sorted(series.items())
                if start_ms <= ts <= end_ms]

    def top_resources(self, app: str, start_ms: int, end_ms: int,
                      limit: int = 30) -> List[str]:
        """Resources ranked by total pass+block volume in the range
        (reference: ``queryTopResourceMetric``'s ordering)."""
        totals: Dict[str, int] = defaultdict(int)
        with self._lock:
            for (a, r), series in self._data.items():
                if a != app:
                    continue
                for ts, e in series.items():
                    if start_ms <= ts <= end_ms:
                        totals[r] += e.pass_qps + e.block_qps
        return [r for r, _ in
                sorted(totals.items(), key=lambda kv: (-kv[1], kv[0]))[:limit]]


class MetricFetcher:
    """Background poller: every healthy machine's /metric -> repository."""

    def __init__(self, apps: AppManagement,
                 repository: Optional[InMemoryMetricsRepository] = None,
                 api: Optional[SentinelApiClient] = None,
                 interval_s: float = 1.0):
        self.apps = apps
        self.repository = repository or InMemoryMetricsRepository()
        self.api = api or SentinelApiClient(timeout_s=2.0)
        self.interval_s = interval_s
        # resume point per machine so seconds aren't double-counted
        self._last_fetched: Dict[str, int] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def fetch_once(self, now_ms: Optional[int] = None) -> int:
        """One sweep over all healthy machines; returns lines ingested."""
        now_ms = now_ms if now_ms is not None else int(time.time() * 1000)
        end = now_ms - FETCH_LAG_MS
        ingested = 0
        # Resume keys are kept for every REGISTERED machine (incl. ones on a
        # transient heartbeat blip — pruning those would re-fetch and
        # double-count seconds when they come back); only machines dead long
        # enough to be purged from the registry are dropped.
        self.apps.purge_dead(now_ms)
        registered = {m.key for app in self.apps.app_names()
                      for m in self.apps.machines(app, include_dead=True)}
        for app in self.apps.app_names():
            for m in self.apps.healthy_machines(app):
                start = self._last_fetched.get(m.key, end - FETCH_SPAN_MS) + 1
                start = max(start, end - FETCH_SPAN_MS)
                if start > end:
                    continue
                try:
                    text = self.api.fetch_metric(m.ip, m.port, start, end)
                except ApiError:
                    continue  # machine down mid-poll; heartbeat will expire it
                newest = self._last_fetched.get(m.key, 0)
                for line in text.splitlines():
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        node = MetricNode.from_thin_string(line)
                    except (ValueError, IndexError):
                        continue
                    self.repository.save(app, node)
                    newest = max(newest, node.timestamp)
                    ingested += 1
                if newest:
                    self._last_fetched[m.key] = newest
        # Machines that churned away (restarts on ephemeral ports) would
        # otherwise accumulate resume keys forever.
        for key in [k for k in self._last_fetched if k not in registered]:
            del self._last_fetched[key]
        self.repository._evict(now_ms)
        return ingested

    def start(self) -> "MetricFetcher":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="dashboard-metric-fetcher", daemon=True)
            self._thread.start()
        return self

    def _run(self):
        while not self._stop.wait(self.interval_s):
            try:
                self.fetch_once()
            except Exception:  # never kill the poll loop
                pass

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

"""Heartbeat sender (reference: ``SimpleHttpHeartbeatSender`` +
``HeartbeatSenderInitFunc`` — SURVEY.md §2.3, §3.4): periodic POST to the
dashboard's ``/registry/machine`` so it discovers this instance and marks it
healthy. Dashboard list comes from ``csp.sentinel.dashboard.server``
(comma-separated ``host:port``); failures rotate to the next address.

Resilience: after a FULL rotation of dashboard addresses fails, the next
beat waits on a seedable ``RetryPolicy`` backoff (base = the heartbeat
interval) instead of hammering dead dashboards at the fixed cadence; one
success restores the cadence.
"""

from __future__ import annotations

import os
import socket
import threading
import urllib.parse
import urllib.request
from typing import List, Optional

from sentinel_tpu.core.config import config
from sentinel_tpu.resilience import RetryPolicy, faults, register_probe
from sentinel_tpu.utils import time_util


def _local_ip() -> str:
    override = config.get("csp.sentinel.heartbeat.client.ip")
    if override:
        return override
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            s.connect(("10.255.255.255", 1))
            return s.getsockname()[0]
        finally:
            s.close()
    except OSError:
        return "127.0.0.1"


class HeartbeatSender:
    def __init__(self, dashboards: Optional[List[str]] = None,
                 interval_ms: Optional[int] = None,
                 api_port: Optional[int] = None,
                 retry_policy: Optional[RetryPolicy] = None):
        servers = dashboards
        if servers is None:
            raw = config.dashboard_server() or ""
            servers = [s.strip() for s in raw.split(",") if s.strip()]
        self.dashboards = servers
        self.interval_ms = interval_ms or config.heartbeat_interval_ms()
        self.api_port = api_port or config.api_port()
        self.retry_policy = retry_policy or RetryPolicy.from_config(
            "heartbeat", base_ms=self.interval_ms,
            max_ms=max(5 * 60_000, self.interval_ms))
        self._retry_session = self.retry_policy.session()
        self.consecutive_failures = 0
        self.last_success_ms = -1
        self._idx = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._probe_off = None

    def health(self) -> dict:
        return {"lastSuccessMs": self.last_success_ms,
                "consecutiveFailures": self.consecutive_failures,
                "intervalMs": self.interval_ms}

    def heartbeat_message(self) -> dict:
        import sentinel_tpu

        return {
            "app": config.app_name(),
            "app_type": str(config.app_type()),
            "v": sentinel_tpu.__version__,
            "version": str(int(__import__("time").time() * 1000)),
            "hostname": socket.gethostname(),
            "ip": _local_ip(),
            "port": str(self.api_port),
            "pid": str(os.getpid()),
        }

    def send_once(self) -> bool:
        """One POST to the current dashboard; rotate on failure."""
        if not self.dashboards:
            return False
        target = self.dashboards[self._idx % len(self.dashboards)]
        url = f"http://{target}/registry/machine"
        data = urllib.parse.urlencode(self.heartbeat_message()).encode("ascii")
        req = urllib.request.Request(url, data=data)
        # Optional shared secret: deployments that enable dashboard auth can
        # also close the (auth-exempt) registration endpoint to strangers.
        from sentinel_tpu.core.config import HEARTBEAT_TOKEN

        token = config.get(HEARTBEAT_TOKEN, "") or ""
        if token:
            req.add_header("X-Sentinel-Heartbeat-Token", token)
        try:
            faults.fire("heartbeat.post")
            if self._post(req):
                # Monotonic: the exported last-success stamp must never
                # run backwards across a dashboard failover (rotating to
                # a dashboard whose clock the frozen test clock — or a
                # skewed host — reports earlier would otherwise make
                # "age since last success" jump negative on scrapes).
                self.last_success_ms = max(
                    self.last_success_ms, time_util.current_time_millis())
                return True
            self._idx += 1
            return False
        except OSError:
            self._idx += 1  # try the next dashboard next beat
            return False

    def _post(self, req) -> bool:
        """The actual POST (seam for tests; overridable)."""
        with urllib.request.urlopen(req, timeout=3) as resp:
            return 200 <= resp.status < 300

    def _next_wait_ms(self, ok: bool) -> int:
        """Cadence governor: steady interval while healthy; once EVERY
        configured dashboard has failed in a row (one full rotation),
        back off — a dead dashboard tier shouldn't eat a POST timeout
        per address per interval forever."""
        if ok:
            self.consecutive_failures = 0
            self._retry_session.reset()
            return self.interval_ms
        self.consecutive_failures += 1
        rotation = max(1, len(self.dashboards))
        if self.consecutive_failures % rotation == 0:
            return max(self.interval_ms, self._retry_session.next_delay_ms())
        return self.interval_ms

    def start(self) -> "HeartbeatSender":
        if self._thread is None:
            self._stop.clear()  # allow start() after a stop()
            self._probe_off = register_probe("heartbeat", self.health)
            self._thread = threading.Thread(
                target=self._run, name="sentinel-heartbeat", daemon=True)
            self._thread.start()
        return self

    def _run(self):
        from sentinel_tpu.log.record_log import record_log

        wait_ms = self.interval_ms
        while not self._stop.wait(wait_ms / 1000.0):
            try:
                ok = self.send_once()
            except Exception as ex:
                ok = False
                record_log.warn("heartbeat failed: %r", ex)
            wait_ms = self._next_wait_ms(ok)

    def stop(self) -> None:
        self._stop.set()
        if self._probe_off is not None:
            self._probe_off()
            self._probe_off = None
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

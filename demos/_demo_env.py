"""Shared demo bootstrap: pin JAX to CPU before anything imports it.

(Remove the pin on a TPU host — everything else is identical.)
"""

import os
import sys

# Repo root on sys.path so the demos run from a checkout without an
# install (sys.path, not PYTHONPATH — the env var breaks TPU-plugin
# discovery on some hosts).
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Force CPU (the host image may pre-set JAX_PLATFORMS to its accelerator);
# export SENTINEL_DEMO_PLATFORM to drive a real device instead.
platform = os.environ.get("SENTINEL_DEMO_PLATFORM", "cpu")
os.environ["JAX_PLATFORMS"] = platform

import jax

jax.config.update("jax_platforms", platform)

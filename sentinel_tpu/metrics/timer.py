"""1 Hz metric aggregation loop (reference:
``core:node/metric/MetricTimerListener.java`` scheduled when the first
ClusterNode appears — SURVEY.md §3.5): pull sealed seconds from the engine
and append them to the metric log.
"""

from __future__ import annotations

import threading
from typing import Optional

from sentinel_tpu.metrics.writer import MetricWriter


class MetricTimerListener:
    def __init__(self, engine=None, writer: Optional[MetricWriter] = None,
                 period_s: float = 1.0):
        # engine=None follows the live default engine (survives reset()).
        self._engine = engine
        self.writer = writer or MetricWriter()
        self.period_s = period_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @property
    def engine(self):
        if self._engine is not None:
            return self._engine
        import sentinel_tpu

        return sentinel_tpu.get_engine()

    def tick(self, now_ms: Optional[int] = None) -> int:
        """One aggregation pass (exposed for deterministic tests).

        Returns the number of lines written.
        """
        nodes = self.engine.seal_metrics(now_ms)
        by_second = {}
        for n in nodes:
            by_second.setdefault(n.timestamp, []).append(n)
        written = 0
        for second in sorted(by_second):
            batch = by_second[second]
            self.writer.write(second, batch)
            written += len(batch)
        return written

    def start(self) -> "MetricTimerListener":
        if self._thread is None:
            self._stop.clear()  # allow start() after a stop()
            self._thread = threading.Thread(
                target=self._run, name="sentinel-metrics-record", daemon=True)
            self._thread.start()
        return self

    def _run(self):
        from sentinel_tpu.log.record_log import record_log

        while not self._stop.wait(self.period_s):
            try:
                self.tick()
            except Exception as ex:  # keep the 1 Hz loop alive, but say why
                record_log.warn("metric timer tick failed: %r", ex)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        self.writer.close()

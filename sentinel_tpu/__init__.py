"""sentinel-tpu: a TPU-native traffic-governance framework.

Capabilities of the reference framework (alibaba/Sentinel fork — see
SURVEY.md): resource entry/exit accounting, sliding-window statistics, flow
rules (reject / warm-up / pacing), circuit breaking, system-adaptive
protection, hot-parameter limiting, dynamic configuration, an ops/metrics
plane, and cluster-wide flow control — re-designed TPU-first: all per-
resource sliding windows live in one HBM-resident tensor updated and
rule-checked by jitted JAX programs, and the global rate limiter is a
``psum`` over the device mesh.

Quick start::

    import sentinel_tpu as st

    st.load_flow_rules([st.FlowRule(resource="getUser", count=20)])
    try:
        with st.entry("getUser"):
            do_work()
    except st.BlockException:
        fallback()
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax

# Millisecond timestamps (epoch) and µs leaky-bucket heads need int64.
# Every hot tensor is explicitly i32/f32, so this only widens time scalars.
jax.config.update("jax_enable_x64", True)

from sentinel_tpu.core import constants
from sentinel_tpu.core.constants import (
    BlockReason,
    EntryType,
    MetricEvent,
    ResourceType,
)
from sentinel_tpu.core.context import enter as context_enter
from sentinel_tpu.core.context import exit_context, get_context
from sentinel_tpu.core.engine import (DeviceDispatchError, EntryHandle,
                                      SentinelEngine)
from sentinel_tpu.core.exceptions import (
    AuthorityException,
    BlockException,
    DegradeException,
    FlowException,
    ParamFlowException,
    SystemBlockException,
)
from sentinel_tpu.models.authority import AuthorityRule
from sentinel_tpu.models.degrade import DegradeRule
from sentinel_tpu.models.flow import FlowRule
from sentinel_tpu.llm import TpsRule
from sentinel_tpu.models.param_flow import ParamFlowItem, ParamFlowRule
from sentinel_tpu.models.system import SystemRule

__version__ = "0.1.0"

_default_engine: Optional[SentinelEngine] = None


def get_engine() -> SentinelEngine:
    global _default_engine
    if _default_engine is None:
        _default_engine = SentinelEngine()
        # doInit AFTER the singleton is installed so @init_func hooks that
        # use this module API configure THIS engine (reference ordering:
        # first SphU.entry triggers InitExecutor once Env is ready).
        from sentinel_tpu.core.spi import run_init_funcs

        run_init_funcs()
    return _default_engine


def reset(capacity: int = 4096) -> SentinelEngine:
    """Tear down and rebuild the default engine (tests)."""
    global _default_engine
    had_engine = _default_engine is not None
    if had_engine:
        _default_engine.close()
    _default_engine = SentinelEngine(capacity)
    if had_engine:
        # Surviving contexts (on ANY thread) hold row ids into the dead
        # engine's registry; the next entry through one would index a
        # foreign (shorter) meta table. The stamp invalidates them all.
        # Bump AFTER installing the new engine: a context created through
        # the old engine mid-reset must carry a pre-bump stamp.
        from sentinel_tpu.core.context import bump_generation

        bump_generation()
    from sentinel_tpu.core.spi import run_init_funcs

    run_init_funcs()
    return _default_engine


def entry(resource: str, entry_type: int = EntryType.OUT, count: int = 1,
          args: Sequence = (), prioritized: bool = False) -> EntryHandle:
    """``SphU.entry``: raises a BlockException subclass when rejected."""
    return get_engine().entry(resource, entry_type, count, args, prioritized)


def entry_ok(resource: str, entry_type: int = EntryType.OUT, count: int = 1,
             args: Sequence = ()) -> Optional[EntryHandle]:
    """``SphO.entry``: boolean variant — None instead of an exception."""
    try:
        return get_engine().entry(resource, entry_type, count, args)
    except BlockException:
        return None


def trace(ex: BaseException) -> None:
    """``Tracer.trace``: record a business exception on the current entry."""
    ctx = get_context()
    if ctx is not None and ctx.cur_entry is not None:
        ctx.cur_entry.trace(ex)


_ops_plane = None


def init_ops_plane(port: Optional[int] = None):
    """Boot the ops plane (reference: ``InitExecutor.doInit`` — SURVEY.md
    §3.4): command HTTP server, heartbeat (when a dashboard is configured),
    and the 1 Hz metric log timer. Idempotent; returns the started parts.
    """
    global _ops_plane
    if _ops_plane is not None:
        return _ops_plane
    from sentinel_tpu.core.config import config as _config
    from sentinel_tpu.metrics.timer import MetricTimerListener
    from sentinel_tpu.transport.command_center import CommandCenter
    from sentinel_tpu.transport.heartbeat import HeartbeatSender

    get_engine()
    # No explicit engine: both follow the live default engine so a later
    # reset() doesn't leave the ops plane serving a dead one.
    center = CommandCenter(port=port).start()
    timer = MetricTimerListener().start()
    heartbeat = None
    if _config.dashboard_server():
        heartbeat = HeartbeatSender(api_port=center.bound_port).start()
    _ops_plane = {"command_center": center, "metric_timer": timer,
                  "heartbeat": heartbeat}
    return _ops_plane


def shutdown_ops_plane() -> None:
    global _ops_plane
    if _ops_plane is None:
        return
    parts, _ops_plane = _ops_plane, None
    parts["command_center"].stop()
    parts["metric_timer"].stop()
    if parts["heartbeat"] is not None:
        parts["heartbeat"].stop()


def load_flow_rules(rules) -> None:
    get_engine().flow_rules.load_rules(list(rules))


def load_degrade_rules(rules) -> None:
    get_engine().degrade_rules.load_rules(list(rules))


def load_authority_rules(rules) -> None:
    get_engine().authority_rules.load_rules(list(rules))


def load_system_rules(rules) -> None:
    get_engine().system_rules.load_rules(list(rules))


def load_param_flow_rules(rules) -> None:
    get_engine().param_rules.load_rules(list(rules))


def load_tps_rules(rules) -> None:
    get_engine().tps_rules.load_rules(list(rules))


from sentinel_tpu.core.checkpoint import (
    CheckpointTimer,
    restore_checkpoint,
    save_checkpoint,
)
from sentinel_tpu.core.spi import (
    EntryInfo,
    ProcessorSlot,
    init_func,
    register_device_checker,
    register_slot,
    unregister_device_checker,
    unregister_slot,
)
from sentinel_tpu import resilience

__all__ = [
    "AuthorityException", "AuthorityRule", "BlockException", "BlockReason",
    "CheckpointTimer", "restore_checkpoint", "save_checkpoint",
    "DegradeException", "DegradeRule", "DeviceDispatchError", "EntryHandle",
    "EntryInfo", "EntryType",
    "FlowException", "FlowRule", "MetricEvent", "ParamFlowException",
    "ParamFlowItem", "ParamFlowRule", "ProcessorSlot", "ResourceType",
    "SentinelEngine", "SystemBlockException", "SystemRule", "constants",
    "context_enter", "entry", "entry_ok", "exit_context", "get_context",
    "get_engine", "init_func", "init_ops_plane", "load_authority_rules",
    "load_degrade_rules", "load_flow_rules", "load_param_flow_rules",
    "load_system_rules", "register_device_checker", "register_slot", "reset",
    "resilience", "shutdown_ops_plane", "trace", "unregister_device_checker",
    "unregister_slot",
]

"""Metric log range reads (reference: ``core:node/metric/MetricSearcher.java``
+ ``MetricsReader.java``): seek by the ``.idx`` second->offset map, stream
lines, filter by time range and optional resource identity.
"""

from __future__ import annotations

import os
from typing import List, Optional

from sentinel_tpu.metrics.metric_node import MetricNode
from sentinel_tpu.metrics.writer import IDX_RECORD, parse_metric_file

DEFAULT_MAX_LINES = 6000


class MetricSearcher:
    def __init__(self, base_dir: str, app: str):
        self.base_dir = base_dir
        self.app = app

    def _data_files(self) -> List[str]:
        try:
            names = os.listdir(self.base_dir)
        except OSError:
            return []
        out = []
        for n in names:
            parsed = parse_metric_file(n)
            if parsed and parsed[0] == self.app:
                out.append(n)
        out.sort(key=lambda n: (parse_metric_file(n)[1], parse_metric_file(n)[2]))
        return [os.path.join(self.base_dir, n) for n in out]

    @staticmethod
    def _seek_offset(idx_path: str, begin_ms: int) -> Optional[int]:
        """Offset of the first second >= begin_ms, or None if file is older."""
        try:
            with open(idx_path, "rb") as f:
                while True:
                    rec = f.read(IDX_RECORD.size)
                    if len(rec) < IDX_RECORD.size:
                        return None
                    second, offset = IDX_RECORD.unpack(rec)
                    if second >= begin_ms:
                        return offset
        except OSError:
            return None

    def find(self, begin_ms: int, recommend_lines: int = DEFAULT_MAX_LINES) -> List[MetricNode]:
        """Reference ``find(beginTimeMs, recommendLines)``: read forward from
        the first second >= begin until the line budget is spent."""
        return self._query(begin_ms, None, None, recommend_lines)

    def find_by_time_and_resource(self, begin_ms: int, end_ms: int,
                                  identity: Optional[str] = None,
                                  max_lines: int = DEFAULT_MAX_LINES) -> List[MetricNode]:
        return self._query(begin_ms, end_ms, identity, max_lines)

    def _query(self, begin_ms, end_ms, identity, max_lines) -> List[MetricNode]:
        out: List[MetricNode] = []
        for path in self._data_files():
            offset = self._seek_offset(path + ".idx", begin_ms)
            if offset is None:
                continue
            try:
                with open(path, "rb") as f:
                    f.seek(offset)
                    for raw in f:
                        try:
                            node = MetricNode.from_thin_string(raw.decode("utf-8"))
                        except (ValueError, UnicodeDecodeError):
                            continue
                        if node.timestamp < begin_ms:
                            continue
                        if end_ms is not None and node.timestamp > end_ms:
                            return out
                        if identity is not None and node.resource != identity:
                            continue
                        out.append(node)
                        if len(out) >= max_lines:
                            return out
            except OSError:
                continue
        return out

"""Consul KV datasource: the blocking-query watch protocol (reference:
``sentinel-datasource-consul``'s ``ConsulDataSource`` — an initial KV get
plus a long-poll watch keyed on ``X-Consul-Index`` — SURVEY.md §2.2).

This speaks the actual Consul HTTP KV API, not an SDK:

- ``GET /v1/kv/<key>`` → JSON array of one entry
  ``{"Key": ..., "Value": <base64>, "ModifyIndex": N, ...}`` with the
  current index mirrored in the ``X-Consul-Index`` response header;
  404 when the key is absent (the header is still present).
- Blocking query: ``GET /v1/kv/<key>?index=<N>&wait=<dur>`` parks until
  ``ModifyIndex > N`` or the wait elapses, then answers with the current
  state (possibly unchanged — the caller compares indexes). ``wait``
  accepts Consul's duration syntax (``10s``, ``1m``).

The connector owns reconnect/backoff and index bookkeeping. Consul's
contract makes missed-update recovery automatic: whatever happened while
the watcher was down is visible in the first reply after reconnect
(state-based, not event-based). Bad payloads keep the last good rules.

``MiniConsulServer`` is the in-repo fake (KV subset with real blocking
queries and index semantics); point the datasource at a real Consul
agent and no line of the connector changes.
"""

from __future__ import annotations

import base64
import json
import re
import time
import urllib.error
import urllib.parse
import urllib.request
from http.server import BaseHTTPRequestHandler
from typing import Dict, Optional, Tuple

from sentinel_tpu.datasource._mini_http import (
    RestartableHTTPServer,
    normalize_base,
)
from sentinel_tpu.datasource.base import (
    AbstractDataSource,
    Converter,
    ReconnectingWatchMixin,
    T,
    WritableDataSource,
    _log_warn,
)


def _parse_wait(raw: str) -> float:
    """Consul duration (``10s`` / ``1m`` / bare seconds) → seconds."""
    m = re.fullmatch(r"(\d+(?:\.\d+)?)(ms|s|m|h)?", raw.strip())
    if not m:
        raise ValueError(f"bad wait duration {raw!r}")
    scale = {"ms": 0.001, "s": 1.0, "m": 60.0, "h": 3600.0,
             None: 1.0}[m.group(2)]
    return float(m.group(1)) * scale


class ConsulDataSource(ReconnectingWatchMixin, AbstractDataSource[str, T]):
    """Initial get + index-keyed blocking-query watch loop.

    ``wait`` is the blocking-query duration advertised to the server
    (Consul default 5m; tests shrink it). The HTTP read timeout stretches
    past it so only a dead agent — not a quiet key — trips reconnect.
    """

    _watch_exceptions = (OSError, urllib.error.URLError, ValueError)
    _watch_thread_name = "sentinel-consul-watch"

    def __init__(self, agent_addr: str, key: str, converter: Converter,
                 wait: str = "30s", token: Optional[str] = None,
                 reconnect_backoff_ms: Tuple[int, int] = (50, 2000)):
        super().__init__(converter)
        self.base = normalize_base(agent_addr)
        self.key = key.lstrip("/")
        self.wait = wait
        # A typo'd duration must fail HERE, not inside every blocking
        # read (where the watch loop would swallow it as an endless
        # reconnect and silently never deliver updates).
        self._wait_s = _parse_wait(wait)
        self.token = token
        self._index = 0          # last X-Consul-Index seen
        self._applied = None     # raw content of the last APPLIED value
        self._init_watch(reconnect_backoff_ms)

    # -- ReadableDataSource ------------------------------------------------

    def _get(self, blocking: bool) -> Tuple[Optional[dict], int]:
        """One KV read → (entry-or-None, X-Consul-Index)."""
        params = {}
        if blocking:
            params = {"index": str(self._index), "wait": self.wait}
        qs = ("?" + urllib.parse.urlencode(params)) if params else ""
        req = urllib.request.Request(
            f"{self.base}/v1/kv/{urllib.parse.quote(self.key)}{qs}")
        if self.token:
            req.add_header("X-Consul-Token", self.token)
        timeout = (self._wait_s + 10.0) if blocking else 5.0
        try:
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                idx = int(resp.headers.get("X-Consul-Index", "0"))
                entries = json.loads(resp.read().decode("utf-8"))
                return (entries[0] if entries else None), idx
        except urllib.error.HTTPError as ex:
            if ex.code == 404:
                idx = int(ex.headers.get("X-Consul-Index", "0") or 0)
                return None, idx
            raise

    def read_source(self) -> Optional[str]:
        entry, _ = self._get(blocking=False)
        if entry is None or entry.get("Value") is None:
            return None
        return base64.b64decode(entry["Value"]).decode("utf-8")

    def start(self) -> "ConsulDataSource":
        try:
            entry, idx = self._get(blocking=False)
            self._index = idx
            self._apply(entry)
        except (OSError, urllib.error.URLError, ValueError) as ex:
            _log_warn("consul datasource initial load failed: %r", ex)
        self._start_watching()
        return self

    def close(self) -> None:
        self._join_watch()

    # -- internals ---------------------------------------------------------

    def _apply(self, entry: Optional[dict]) -> None:
        if entry is None or entry.get("Value") is None or self._stop.is_set():
            return
        try:
            content = base64.b64decode(entry["Value"]).decode("utf-8")
        except Exception as ex:
            _log_warn("consul datasource bad payload: %r", ex)
            return
        # Dedup on CONTENT, not ModifyIndex: a wait that elapses idle
        # re-delivers the same value (Consul's normal case), and an index
        # reset (leader change) can reuse an old index for NEW content —
        # only the bytes say whether anything actually changed.
        if content == self._applied:
            return
        try:
            value = self.converter(content)
        except Exception as ex:  # keep last good rules
            _log_warn("consul datasource bad payload: %r", ex)
            return
        if value is not None:
            self._property.update_value(value)
            self._applied = content

    def _watch_round(self) -> None:
        entry, idx = self._get(blocking=True)
        # Consul contract: a reset index (e.g. leader change / restarted
        # fake) must restart the watch from scratch.
        self._index = idx if idx >= self._index else 0
        self._apply(entry)
        self._healthy()


class ConsulWritableDataSource(WritableDataSource[T]):
    """Publish via ``PUT /v1/kv/<key>`` (raw body, like the reference's
    writer)."""

    def __init__(self, agent_addr: str, key: str, encoder: Converter,
                 token: Optional[str] = None):
        self.base = normalize_base(agent_addr)
        self.key = key.lstrip("/")
        self.encoder = encoder
        self.token = token

    def write(self, value: T) -> None:
        req = urllib.request.Request(
            f"{self.base}/v1/kv/{urllib.parse.quote(self.key)}",
            data=self.encoder(value).encode("utf-8"), method="PUT")
        if self.token:
            req.add_header("X-Consul-Token", self.token)
        with urllib.request.urlopen(req, timeout=5.0) as resp:
            if resp.read().decode("utf-8").strip() != "true":
                raise OSError("consul put rejected")


# -- in-repo fake server ------------------------------------------------------


class _ConsulHandler(BaseHTTPRequestHandler):
    def _send(self, code: int, body: bytes, index: int,
              ctype: str = "application/json") -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("X-Consul-Index", str(index))
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):  # noqa: N802 — http.server API
        server: "MiniConsulServer" = self.server  # type: ignore
        path, _, query = self.path.partition("?")
        if not path.startswith("/v1/kv/"):
            return self._send(404, b"[]", 0)
        key = urllib.parse.unquote(path[len("/v1/kv/"):])
        q = urllib.parse.parse_qs(query)
        want_index = int(q.get("index", ["0"])[0] or 0)
        wait_s = 0.0
        if "index" in q:
            wait_s = min(_parse_wait(q.get("wait", ["5m"])[0]),
                         server.max_hold_ms / 1000.0)

        deadline = time.monotonic() + wait_s
        with server._cond:
            if wait_s > 0:
                server.poll_rounds += 1
            while True:
                entry = server._kv.get(key)
                cur = entry[1] if entry else 0
                remaining = deadline - time.monotonic()
                if (cur > want_index or remaining <= 0
                        or server._stopping):
                    break
                server._cond.wait(min(remaining, 0.25))
            global_index = server._index
            if entry is None:
                return self._send(404, b"[]", global_index)
            value, modify = entry
            body = json.dumps([{
                "Key": key,
                "Value": base64.b64encode(value).decode("ascii"),
                "ModifyIndex": modify, "CreateIndex": modify,
                "Flags": 0, "LockIndex": 0,
            }]).encode("utf-8")
        self._send(200, body, max(global_index, modify))

    def do_PUT(self):  # noqa: N802 — http.server API
        server: "MiniConsulServer" = self.server  # type: ignore
        path = self.path.partition("?")[0]
        if not path.startswith("/v1/kv/"):
            return self._send(404, b"false", 0)
        key = urllib.parse.unquote(path[len("/v1/kv/"):])
        n = int(self.headers.get("Content-Length", "0"))
        value = self.rfile.read(n)
        with server._cond:
            server._index += 1
            server._kv[key] = (value, server._index)
            server._cond.notify_all()
            idx = server._index
        self._send(200, b"true", idx)

    def log_message(self, fmt, *args):  # quiet
        pass


class MiniConsulServer(RestartableHTTPServer):
    """Consul KV subset with real blocking queries and index semantics.

    ``stop()`` + ``start()`` rebinds the same port for reconnect tests;
    the KV (and its indexes) survive the restart, like a real agent
    backed by its servers. ``max_hold_ms`` caps blocking-query parking so
    tests never wait a client-advertised 5m.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 max_hold_ms: int = 30000):
        super().__init__(host, port, _ConsulHandler)
        self.max_hold_ms = max_hold_ms
        self._kv: Dict[str, Tuple[bytes, int]] = {}  # key -> (value, index)
        self._index = 0

    def put(self, key: str, value: str) -> None:
        with self._cond:
            self._index += 1
            self._kv[key.lstrip("/")] = (value.encode("utf-8"), self._index)
            self._cond.notify_all()

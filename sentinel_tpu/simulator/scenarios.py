"""Seedable synthetic scenario generators.

Every generator is a pure function of its parameters plus one
``numpy.random.default_rng(seed)`` stream, so a (name, seconds, seed,
params) tuple names exactly one trace forever — the scenario-diversity
engine the ROADMAP wants every future subsystem validated against.

Shapes covered (ISSUE 13):

* ``diurnal`` — a smooth load cycle (cosine day compressed to
  ``period_s``) with Poisson noise: the baseline-drift case.
* ``flash_crowd`` — a step burst of ``crowd`` tokens/s for ``width_s``
  seconds on top of a calm base: the under-provisioned-limit case the
  adaptive loop must open fast.
* ``retry_storm`` — an overload burst whose BLOCKED demand re-offers
  after a backoff with a decay factor (``meta["retry"]``): the one
  closed-loop coupling a real recorded trace cannot carry, implemented
  by the replay engine itself.
* ``correlated_overload`` — several resources spiking in the SAME
  seconds: the multi-resource blast-radius case (one resource's tuning
  must not be judged on another's alerts).
* ``hetero_cost`` — SLINFER-style heterogeneous inference costs: mixed
  acquire counts per entry (small chat / medium completion / large
  batch-prompt classes) against shared per-model budgets.

Load-dependent RT: generators attach ``meta["rtProfile"][resource] =
{"baseMs", "loadedMs", "kneeTps"}`` — the replay engine stamps admitted
tokens beyond the knee with the loaded RT, so over-admission shows up in
the scored RT-p99 exactly like a congested backend would show it.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

# The ONE canary-epoch definition (core/config.py): synthetic traces
# live far from the wall clock, aligned to a second boundary, so an
# ambient clock read in a replayed path is instantly wrong.
from sentinel_tpu.core.config import DEFAULT_SIM_EPOCH_MS as DEFAULT_EPOCH_MS
from sentinel_tpu.simulator.trace import Trace


def _flow_rule(resource: str, count: float) -> Dict:
    """A plain tunable QPS rule (the shape the adaptive loop may
    retune: direct strategy, default control behavior / limit app)."""
    return {"resource": resource, "grade": 1, "count": float(count),
            "strategy": 0, "controlBehavior": 0, "limitApp": "default"}


def _seconds_from_demand(demand: Dict[str, np.ndarray],
                         counts: Optional[Dict[str, List[List[int]]]] = None
                         ) -> List[Dict]:
    """Per-resource tokens/s vectors -> sparse trace seconds. ``counts``
    optionally splits a resource's tokens into an acquire-count mix
    ([[count, weight], ...]; weights are relative)."""
    n = max(len(v) for v in demand.values())
    seconds = []
    for t in range(n):
        d: Dict[str, list] = {}
        for res in sorted(demand):
            tokens = int(demand[res][t]) if t < len(demand[res]) else 0
            if tokens <= 0:
                continue
            mix = (counts or {}).get(res)
            if not mix:
                d[res] = [[1, tokens]]
                continue
            # Deterministic split: weight-proportional tokens per class,
            # remainder tokens to the smallest count class as 1-token
            # acquires would misstate the mix — they go to the first.
            total_w = sum(w for _, w in mix)
            pairs = []
            used = 0
            for count, w in mix:
                share = int(tokens * w / total_w)
                entries = share // count
                if entries:
                    pairs.append([count, entries])
                    used += entries * count
            rest = tokens - used
            if rest > 0:
                pairs.append([1, rest])
            d[res] = pairs
        if d:
            seconds.append({"t": t, "d": d})
    return seconds


def diurnal(seconds: int = 240, seed: int = 0, base: float = 40,
            peak: float = 200, period_s: int = 120,
            limit: float = 120) -> Trace:
    rng = np.random.default_rng(seed)
    t = np.arange(seconds)
    mean = base + (peak - base) * 0.5 * (1 - np.cos(2 * np.pi * t / period_s))
    demand = rng.poisson(mean).astype(np.int64)
    return Trace(
        epoch_ms=DEFAULT_EPOCH_MS, duration_s=seconds,
        meta={"scenario": "diurnal", "seed": seed,
              "rtProfile": {"web": {"baseMs": 8, "loadedMs": 40,
                                    "kneeTps": int(limit * 2)}}},
        resources=["web"],
        rules={"flow": [_flow_rule("web", limit)]},
        seconds=_seconds_from_demand({"web": demand}))


def flash_crowd(seconds: int = 240, seed: int = 0, base: float = 30,
                crowd: float = 400, at_s: Optional[int] = None,
                width_s: Optional[int] = None, limit: float = 50) -> Trace:
    rng = np.random.default_rng(seed)
    at = seconds // 4 if at_s is None else at_s
    width = seconds // 2 if width_s is None else width_s
    mean = np.full(seconds, base, np.float64)
    mean[at:at + width] += crowd
    demand = rng.poisson(mean).astype(np.int64)
    return Trace(
        epoch_ms=DEFAULT_EPOCH_MS, duration_s=seconds,
        meta={"scenario": "flash_crowd", "seed": seed,
              "crowd": {"atS": at, "widthS": width},
              "rtProfile": {"web": {"baseMs": 10, "loadedMs": 60,
                                    "kneeTps": int(crowd * 2)}}},
        resources=["web"],
        rules={"flow": [_flow_rule("web", limit)]},
        seconds=_seconds_from_demand({"web": demand}))


def retry_storm(seconds: int = 240, seed: int = 0, base: float = 40,
                burst: float = 300, at_s: Optional[int] = None,
                width_s: int = 20, limit: float = 60,
                backoff_s: int = 2, factor: float = 0.7,
                max_attempts: int = 3) -> Trace:
    """Overload burst + client retries: blocked demand re-offers after
    ``backoff_s`` at ``factor`` strength, up to ``max_attempts`` — the
    replay engine closes this loop (``meta["retry"]``), so a policy that
    opens the limit faster also drains the storm faster."""
    rng = np.random.default_rng(seed)
    at = seconds // 4 if at_s is None else at_s
    mean = np.full(seconds, base, np.float64)
    mean[at:at + width_s] += burst
    demand = rng.poisson(mean).astype(np.int64)
    return Trace(
        epoch_ms=DEFAULT_EPOCH_MS, duration_s=seconds,
        meta={"scenario": "retry_storm", "seed": seed,
              "retry": {"backoffSeconds": int(backoff_s),
                        "factor": float(factor),
                        "maxAttempts": int(max_attempts)},
              "rtProfile": {"api": {"baseMs": 12, "loadedMs": 80,
                                    "kneeTps": int(burst * 2)}}},
        resources=["api"],
        rules={"flow": [_flow_rule("api", limit)]},
        seconds=_seconds_from_demand({"api": demand}))


def correlated_overload(seconds: int = 240, seed: int = 0,
                        resources: int = 3, base: float = 30,
                        surge: float = 150, at_s: Optional[int] = None,
                        width_s: Optional[int] = None,
                        limit: float = 45) -> Trace:
    """All resources surge in the SAME window (a shared upstream event):
    the case where per-resource tuning must hold under a fleet-wide
    spike and one resource's alerts must not gate the others' retunes."""
    rng = np.random.default_rng(seed)
    at = seconds // 3 if at_s is None else at_s
    width = seconds // 3 if width_s is None else width_s
    names = [f"svc{i}" for i in range(resources)]
    demand = {}
    for i, name in enumerate(names):
        mean = np.full(seconds, base * (1 + 0.2 * i), np.float64)
        mean[at:at + width] += surge * (1 + 0.1 * i)
        demand[name] = rng.poisson(mean).astype(np.int64)
    return Trace(
        epoch_ms=DEFAULT_EPOCH_MS, duration_s=seconds,
        meta={"scenario": "correlated_overload", "seed": seed,
              "rtProfile": {name: {"baseMs": 10, "loadedMs": 50,
                                   "kneeTps": int(surge * 3)}
                            for name in names}},
        resources=names,
        rules={"flow": [_flow_rule(name, limit) for name in names]},
        seconds=_seconds_from_demand(demand))


def hetero_cost(seconds: int = 240, seed: int = 0, base_tokens: float = 200,
                swing: float = 0.5, period_s: int = 80,
                limit: float = 240, streams_per_s: float = 0.0,
                stream_len_s: int = 6, stream_tokens: int = 120,
                abandon_rate: float = 0.0) -> Trace:
    """SLINFER-style heterogeneous inference admission: two model
    resources sharing the token-per-second currency, each second's
    demand split into acquire-count classes (chat=1, completion=4,
    batch-prompt=16 tokens) in model-specific proportions — the
    mixed-count fixpoint regime of the fused step, driven at scale.

    Streamed-generation mode (ISSUE 17, opt-in — ``streams_per_s > 0``):
    the scenario switches to the TPS rule family (``llm:*`` lowered
    resources) and adds Poisson streamed-generation arrivals — each
    stream opens with a ``stream_tokens`` estimate, ticks its output
    down over ``stream_len_s`` seconds, and closes; ``abandon_rate``
    of streams abort mid-generation with their reservation
    unreconciled (the over-admission bound's stress knob). All stream
    draws happen AFTER the demand draws, so the default
    (``streams_per_s=0``) trace stays bit-identical to pre-ISSUE-17
    captures."""
    rng = np.random.default_rng(seed)
    t = np.arange(seconds)
    wave = 1 + swing * np.sin(2 * np.pi * t / period_s)
    streamed = streams_per_s > 0
    prefix = "llm:" if streamed else ""
    small, large = prefix + "model-small", prefix + "model-large"
    demand = {
        small: rng.poisson(base_tokens * wave).astype(np.int64),
        # The large model trails by half a period (tenants shift load).
        large: rng.poisson(
            base_tokens * 0.6 * (2 - wave)).astype(np.int64),
    }
    counts = {
        small: [[1, 6], [4, 3]],         # chat-heavy
        large: [[4, 2], [16, 3], [1, 1]],  # long generations
    }
    secs = _seconds_from_demand(demand, counts)
    meta = {"scenario": "hetero_cost", "seed": seed,
            "countClasses": counts,
            "rtProfile": {
                small: {"baseMs": 30, "loadedMs": 250,
                        "kneeTps": int(base_tokens * 2)},
                large: {"baseMs": 120, "loadedMs": 900,
                        "kneeTps": int(base_tokens)}}}
    if not streamed:
        return Trace(
            epoch_ms=DEFAULT_EPOCH_MS, duration_s=seconds,
            meta=meta, resources=[small, large],
            rules={"flow": [_flow_rule(small, limit),
                            _flow_rule(large, limit * 0.6)]},
            seconds=secs)
    # Streamed-generation arrivals: all draws AFTER the demand draws,
    # in a fixed (model-sorted, time-ordered) sequence — one seed names
    # one event schedule forever.
    meta["streams"] = {"perS": float(streams_per_s),
                       "lenS": int(stream_len_s),
                       "tokens": int(stream_tokens),
                       "abandonRate": float(abandon_rate)}
    by_t: Dict[int, list] = {}
    sid = 0
    for model in ("model-large", "model-small"):
        arrivals = rng.poisson(streams_per_s, seconds)
        for t0 in range(seconds):
            for _ in range(int(arrivals[t0])):
                sid += 1
                stream_id = f"g{sid}"
                length = max(1, int(stream_len_s))
                per_tick = max(1, int(np.ceil(stream_tokens / length)))
                aborts = bool(rng.random() < abandon_rate)
                # An aborted stream dies after a prefix of its ticks,
                # leaving the rest of its reservation unreconciled.
                live_ticks = length if not aborts else \
                    1 + int(rng.random() * max(1, length - 1))
                by_t.setdefault(t0, []).append(
                    {"op": "open", "id": stream_id, "model": model,
                     "est": int(stream_tokens)})
                left = int(stream_tokens)
                end_t = t0
                for k in range(1, live_ticks + 1):
                    tk = t0 + k
                    if tk >= seconds:
                        break
                    tok = min(per_tick, left) if k < length else left
                    by_t.setdefault(tk, []).append(
                        {"op": "tick", "id": stream_id, "tok": int(tok)})
                    left -= int(tok)
                    end_t = tk
                close_t = min(end_t + 1, seconds - 1)
                by_t.setdefault(close_t, []).append(
                    {"op": "abort" if aborts else "close",
                     "id": stream_id})
    sec_by_t = {s["t"]: s for s in secs}
    for t0, events in by_t.items():
        rec = sec_by_t.get(t0)
        if rec is None:
            rec = {"t": t0, "d": {}}
            sec_by_t[t0] = rec
        rec["g"] = events
    return Trace(
        epoch_ms=DEFAULT_EPOCH_MS, duration_s=seconds,
        meta=meta, resources=[small, large],
        rules={"tps": [
            {"model": "model-small", "tokensPerSecond": float(limit),
             "burstTokens": 0.0, "maxConcurrentStreams": 0},
            {"model": "model-large",
             "tokensPerSecond": float(limit * 0.6),
             "burstTokens": 0.0, "maxConcurrentStreams": 0},
        ]},
        seconds=sorted(sec_by_t.values(), key=lambda s: s["t"]))


SCENARIOS = {
    "diurnal": diurnal,
    "flash_crowd": flash_crowd,
    "retry_storm": retry_storm,
    "correlated_overload": correlated_overload,
    "hetero_cost": hetero_cost,
}


def build_scenario(name: str, seconds: Optional[int] = None,
                   seed: int = 0, **params) -> Trace:
    """Build a named scenario trace; unknown names raise with the
    catalog (the ``sim`` command's error surface)."""
    builder = SCENARIOS.get(name)
    if builder is None:
        raise ValueError(
            f"unknown scenario {name!r} (have: {sorted(SCENARIOS)})")
    if seconds is not None:
        params["seconds"] = int(seconds)
    return builder(seed=seed, **params)

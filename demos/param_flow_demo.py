"""Hot-parameter demo (reference: ``sentinel-demo-parameter-flow-control``):
per-value quotas — a hot user is limited while everyone else passes, and a
ParamFlowItem grants one VIP a higher quota."""

import _demo_env  # noqa: F401

import sentinel_tpu as st

st.load_param_flow_rules([st.ParamFlowRule(
    "getUser", param_idx=0, count=2,
    items=[st.ParamFlowItem(object="vip", count=100)])])

# One throwaway call absorbs the XLA compile (~30s on CPU) so the loop
# below runs inside a single one-second window.
h = st.entry_ok("getUser", args=["_warmup"])
if h:
    h.exit()

for user in ["alice", "alice", "alice", "bob", "vip", "vip", "vip", "vip"]:
    ok = st.entry_ok("getUser", args=[user])
    print(f"getUser({user!r}) -> {'pass' if ok else 'BLOCKED'}")
    if ok:
        ok.exit()

package com.alibaba.csp.sentinel.context;

import com.alibaba.csp.sentinel.Entry;
import com.alibaba.csp.sentinel.node.DefaultNode;

/** Vendored signature stub (see vendored/README.md). Reference:
 * core:context/Context.java — only the members the bridge touches. */
public class Context {

    private final String name;
    private DefaultNode entranceNode;
    private Entry curEntry;
    private String origin = "";
    private final boolean async;

    public Context(DefaultNode entranceNode, String name) {
        this.name = name;
        this.entranceNode = entranceNode;
        this.async = false;
    }

    public String getName() {
        return name;
    }

    public String getOrigin() {
        return origin;
    }

    public Context setOrigin(String origin) {
        this.origin = origin;
        return this;
    }

    public Entry getCurEntry() {
        return curEntry;
    }

    public Context setCurEntry(Entry curEntry) {
        this.curEntry = curEntry;
        return this;
    }

    public DefaultNode getEntranceNode() {
        return entranceNode;
    }

    public boolean isAsync() {
        return async;
    }
}

"""Deterministic canary assignment for staged rollouts.

A request's rollout stage must be STABLE: the same caller (context,
origin) lands on the same side of the canary split on every step, or a
paced client would flap between the live and candidate rulesets and see
neither's semantics. There is no reference twin — the reference has no
staged rollout; the closest analog is its ``limitApp`` origin routing,
which is why the canary key is the same (origin, context) pair the flow
checker already carries on device.

The assignment is a pure function of (origin_id, context_id, salt): a
32-bit multiplicative mix hashed into basis points and compared against
the candidate's ``canary_bps`` knob. It runs identically under numpy on
the host (tests, ops introspection) and jnp inside the fused step —
both go through the same arithmetic below, so host predictions match
device verdicts bit-for-bit.
"""

from __future__ import annotations

# Odd multiplicative constants (Knuth / murmur-finalizer lineage), same
# family param_flow's CMS hashes use. Arithmetic is mod 2^32 throughout.
_MIX_A = 0x9E3779B1
_MIX_B = 0x85EBCA77
_MIX_C = 0xC2B2AE3D

CANARY_BPS_MAX = 10_000  # basis points: 10000 == 100% of traffic


def canary_hash(origin_id, context_id, salt):
    """uint32 mix of the canary key. Works on python ints, numpy arrays
    and jnp arrays alike (all ops are +, *, ^, >> on uint32).

    origin_id may be negative (ORIGIN_ID_NONE / padding); the +0x101
    offset keeps distinct small negatives distinct after the uint cast.
    """
    h = ((origin_id + 0x101) * _MIX_A + (context_id + 0x7F) * _MIX_B) & 0xFFFFFFFF
    h ^= (salt * _MIX_C) & 0xFFFFFFFF
    h = (h ^ (h >> 15)) * _MIX_B & 0xFFFFFFFF
    h ^= h >> 13
    return h & 0xFFFFFFFF


def canary_bucket(origin_id, context_id, salt):
    """Basis-point bucket in [0, 10000) for the canary key."""
    return canary_hash(origin_id, context_id, salt) % CANARY_BPS_MAX


def in_canary(origin_id, context_id, salt, bps):
    """True when the key falls inside the canary slice of ``bps`` basis
    points. ``bps=0`` selects nobody, ``bps=10000`` everybody."""
    return canary_bucket(origin_id, context_id, salt) < bps


def device_in_canary(origin_id, context_id, salt, bps):
    """jnp variant for the fused step: bool[N] from int32[N] batch lanes.

    Mirrors :func:`in_canary` exactly — the arithmetic is uint32 modular
    either way, so a host-side ``in_canary`` prediction for a key equals
    the device verdict.
    """
    import jax.numpy as jnp

    o = origin_id.astype(jnp.uint32) + jnp.uint32(0x101)
    c = context_id.astype(jnp.uint32) + jnp.uint32(0x7F)
    h = o * jnp.uint32(_MIX_A) + c * jnp.uint32(_MIX_B)
    h = h ^ (jnp.asarray(salt).astype(jnp.uint32) * jnp.uint32(_MIX_C))
    h = (h ^ (h >> jnp.uint32(15))) * jnp.uint32(_MIX_B)
    h = h ^ (h >> jnp.uint32(13))
    bucket = h % jnp.uint32(CANARY_BPS_MAX)
    bps_u = jnp.asarray(bps).astype(jnp.uint32)
    return bucket < bps_u

"""gRPC interceptors (reference: ``sentinel-grpc-adapter``'s
``SentinelGrpcServerInterceptor`` + ``SentinelGrpcClientInterceptor`` —
SURVEY.md §2.5): the server side wraps every inbound RPC in a
``ContextUtil.enter`` + ``entry(method, IN)`` and answers blocked calls
with RESOURCE_EXHAUSTED; the client side guards outbound RPCs with
``entry(method, OUT)`` and traces failures. Resource name = the full RPC
method (``/pkg.Service/Method``), matching the reference's naming.
"""

from __future__ import annotations

from typing import Callable, Optional

import grpc  # this module, like the reference's grpc adapter, requires it

import sentinel_tpu as st
from sentinel_tpu.core import constants as C
from sentinel_tpu.core.exceptions import BlockException

GRPC_CONTEXT_NAME = "sentinel_grpc_context"
ORIGIN_METADATA_KEY = "sentinel-origin"  # caller app, like dubbo's attachment


def _origin_from_metadata(metadata) -> str:
    for key, value in metadata or ():
        if key == ORIGIN_METADATA_KEY:
            return value
    return ""


class SentinelGrpcServerInterceptor(grpc.ServerInterceptor):
    """``grpc.ServerInterceptor``: guard every inbound unary/streaming RPC.

    Add to the server: ``grpc.server(..., interceptors=[
    SentinelGrpcServerInterceptor()])``.
    """

    def __init__(self, fallback: Optional[Callable] = None):
        self._grpc = grpc
        self._fallback = fallback

    def intercept_service(self, continuation, handler_call_details):
        handler = continuation(handler_call_details)
        if handler is None:
            return None
        method = handler_call_details.method
        origin = _origin_from_metadata(
            getattr(handler_call_details, "invocation_metadata", ()))
        grpc = self._grpc
        fallback = self._fallback

        def guard(behavior):
            """Unary-response guard: entry spans the behavior call; the
            with-block auto-traces a raised business exception."""

            def guarded(request_or_iterator, context):
                st.context_enter(GRPC_CONTEXT_NAME, origin)
                try:
                    try:
                        handle = st.entry(method, entry_type=C.EntryType.IN)
                    except BlockException as ex:
                        if fallback is not None:
                            return fallback(request_or_iterator, context, ex)
                        context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED,
                                      f"Blocked by Sentinel: {ex}")
                    with handle:
                        return behavior(request_or_iterator, context)
                finally:
                    st.exit_context()

            return guarded

        def guard_streaming(behavior):
            """Response-streaming guard: the behavior returns a generator,
            so the entry must stay live ACROSS the iteration — otherwise
            long streams are invisible to concurrency rules, RT is ~0, and
            mid-stream failures never reach exception metrics. gRPC's sync
            server iterates the response on the same worker thread, so the
            thread-local context holds."""

            def guarded(request_or_iterator, context):
                st.context_enter(GRPC_CONTEXT_NAME, origin)
                try:
                    try:
                        handle = st.entry(method, entry_type=C.EntryType.IN)
                    except BlockException as ex:
                        if fallback is not None:
                            return fallback(request_or_iterator, context, ex)
                        context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED,
                                      f"Blocked by Sentinel: {ex}")
                except BaseException:
                    st.exit_context()
                    raise

                def stream():
                    try:
                        with handle:  # auto-traces mid-stream exceptions
                            for item in behavior(request_or_iterator, context):
                                yield item
                    finally:
                        st.exit_context()

                return stream()

            return guarded

        # Rewrap whichever behavior kind this handler carries.
        if handler.unary_unary:
            return grpc.unary_unary_rpc_method_handler(
                guard(handler.unary_unary),
                request_deserializer=handler.request_deserializer,
                response_serializer=handler.response_serializer)
        if handler.unary_stream:
            return grpc.unary_stream_rpc_method_handler(
                guard_streaming(handler.unary_stream),
                request_deserializer=handler.request_deserializer,
                response_serializer=handler.response_serializer)
        if handler.stream_unary:
            return grpc.stream_unary_rpc_method_handler(
                guard(handler.stream_unary),
                request_deserializer=handler.request_deserializer,
                response_serializer=handler.response_serializer)
        return grpc.stream_stream_rpc_method_handler(
            guard_streaming(handler.stream_stream),
            request_deserializer=handler.request_deserializer,
            response_serializer=handler.response_serializer)


class SentinelGrpcClientInterceptor(grpc.UnaryUnaryClientInterceptor):
    """``grpc.UnaryUnaryClientInterceptor``: guard outbound RPCs.

    ``grpc.intercept_channel(channel, SentinelGrpcClientInterceptor())``.
    A blocked call raises the BlockException to the caller (the reference
    fails the future with the StatusRuntimeException analog); RPC errors
    feed exception metrics via ``trace``.
    """

    def __init__(self):
        self._grpc = grpc

    def intercept_unary_unary(self, continuation, client_call_details, request):
        method = client_call_details.method
        if isinstance(method, bytes):
            method = method.decode("utf-8", "replace")
        handle = st.entry(method, entry_type=C.EntryType.OUT)
        try:
            call = continuation(client_call_details, request)
        except BaseException as ex:
            handle.trace(ex)
            handle.exit()
            raise
        ok_code = self._grpc.StatusCode.OK

        def _on_done(completed):
            # Asynchronous completion: Call.code() BLOCKS until the status
            # is known, so it must never run inline — a .future() caller
            # would have every launch serialized behind its own RPC.
            try:
                if completed.code() != ok_code:
                    handle.trace(RuntimeError(f"rpc failed: {completed.code()}"))
            finally:
                handle.exit()

        call.add_done_callback(_on_done)
        return call

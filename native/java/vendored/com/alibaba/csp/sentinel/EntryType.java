package com.alibaba.csp.sentinel;

/** Vendored signature stub (see vendored/README.md). Reference:
 * core:EntryType.java. */
public enum EntryType {
    IN,
    OUT
}

"""Push-based dynamic configuration (reference: ``core:property/`` —
``SentinelProperty``, ``DynamicSentinelProperty``, ``PropertyListener``,
``SimplePropertyListener``; SURVEY.md §2.1 "Property system", §3.2).

A property is a typed holder whose ``update_value`` fans out to listeners;
rule managers register as listeners, datasources push into the property.
``update_value`` returns False (and skips the fan-out) when the value is
unchanged — the reference's equality short-circuit.
"""

from __future__ import annotations

import threading
from typing import Callable, Generic, List, Optional, TypeVar

T = TypeVar("T")


class PropertyListener(Generic[T]):
    """Reference: ``PropertyListener<T>``."""

    def config_update(self, value: T) -> None:
        raise NotImplementedError

    def config_load(self, value: T) -> None:
        # Initial load; the default mirrors the reference's common pattern.
        self.config_update(value)


class SimplePropertyListener(PropertyListener[T]):
    def __init__(self, fn: Callable[[T], None]):
        self._fn = fn

    def config_update(self, value: T) -> None:
        self._fn(value)


class SentinelProperty(Generic[T]):
    """Reference: ``SentinelProperty<T>`` interface."""

    def add_listener(self, listener: PropertyListener[T]) -> None:
        raise NotImplementedError

    def remove_listener(self, listener: PropertyListener[T]) -> None:
        raise NotImplementedError

    def update_value(self, value: T) -> bool:
        raise NotImplementedError


class DynamicSentinelProperty(SentinelProperty[T]):
    """Reference: ``DynamicSentinelProperty<T>``.

    ``epoch`` counts ACCEPTED updates (the equality short-circuit does
    not bump it) — a monotonic version observers can compare without
    holding the value itself. The staged-rollout manager uses the same
    scheme for promotion epochs: a promote is one accepted wholesale
    update through this property path, observable as one epoch step.
    """

    def __init__(self, value: Optional[T] = None):
        self._lock = threading.RLock()
        self._listeners: List[PropertyListener[T]] = []
        self.value: Optional[T] = value
        self.epoch = 0

    def add_listener(self, listener: PropertyListener[T]) -> None:
        with self._lock:
            self._listeners.append(listener)
            value = self.value
        if value is not None:
            listener.config_load(value)

    def remove_listener(self, listener: PropertyListener[T]) -> None:
        with self._lock:
            if listener in self._listeners:
                self._listeners.remove(listener)

    def update_value(self, value: T) -> bool:
        with self._lock:
            if value == self.value:
                return False
            self.value = value
            self.epoch += 1
            listeners = list(self._listeners)
        for l in listeners:
            l.config_update(value)
        return True


class NoOpSentinelProperty(SentinelProperty[T]):
    """Reference: ``NoOpPropertyListener`` counterpart for disabled paths."""

    def add_listener(self, listener: PropertyListener[T]) -> None:
        pass

    def remove_listener(self, listener: PropertyListener[T]) -> None:
        pass

    def update_value(self, value: T) -> bool:
        return False

"""Cross-process spans: W3C-traceparent-style trace context + host-side
span collection with an OTLP-flavored JSON export.

PR 3's decision traces stop at the engine: a sampled blocked entry shows
WHAT the verdict was, but when the verdict came from the cluster token
server the round-trip that decided it is invisible. This module carries
a trace context across the cluster wire (``cluster/codec.py`` appends it
as a trailing TLV the old decoders ignore — wire-compatible with old
peers) so one sampled entry stitches:

    engine decision span  ->  token_request span (client wall)
                          ->  token_service span (server-side, shipped
                              back in the response TLV with its own
                              timing)

All spans of a trace share one 128-bit trace id; per-hop timings fall
out of the client/server span walls (client wall minus server duration
= wire + queue overhead). Sampling is independent of the blocked-entry
trace ring (``csp.sentinel.telemetry.spans.sampleEvery``; the cluster
path is pre-verdict, so sampling cannot condition on "blocked").

The context format follows W3C trace-context (``00-<trace32>-<span16>-
<flags2>``) so exported spans join external tracing backends unchanged.
"""

from __future__ import annotations

import secrets
import threading
import time
from typing import Dict, List, NamedTuple, Optional

from sentinel_tpu.utils import time_util

TRACEPARENT_VERSION = "00"


class TraceContext(NamedTuple):
    """One hop's identity inside a trace (immutable; children fork)."""

    trace_id: str   # 32 lowercase hex chars (128-bit)
    span_id: str    # 16 lowercase hex chars (64-bit)
    flags: int = 1  # W3C trace-flags; bit 0 = sampled

    def traceparent(self) -> str:
        return (f"{TRACEPARENT_VERSION}-{self.trace_id}-{self.span_id}"
                f"-{self.flags:02x}")

    def child(self) -> "TraceContext":
        """Same trace, fresh span id — the context a downstream hop gets."""
        return TraceContext(self.trace_id, secrets.token_hex(8), self.flags)


def new_trace_context() -> TraceContext:
    return TraceContext(secrets.token_hex(16), secrets.token_hex(8), 1)


def parse_traceparent(value: str) -> Optional[TraceContext]:
    """Strict-enough parse of ``00-<trace>-<span>-<flags>``; None on any
    malformation (a bad peer costs itself the trace, never the caller)."""
    parts = value.strip().split("-")
    if len(parts) != 4:
        return None
    version, trace_id, span_id, flags = parts
    if len(version) != 2 or len(trace_id) != 32 or len(span_id) != 16 \
            or len(flags) != 2:
        return None
    try:
        int(trace_id, 16), int(span_id, 16)
        flag_bits = int(flags, 16)
    except ValueError:
        return None
    if trace_id == "0" * 32 or span_id == "0" * 16:
        return None
    return TraceContext(trace_id.lower(), span_id.lower(), flag_bits)


class Span:
    """One timed operation. Mutable until :meth:`finish`; host-side only."""

    __slots__ = ("trace_id", "span_id", "parent_span_id", "name",
                 "start_ms", "duration_us", "attrs", "_t0")

    def __init__(self, name: str, ctx: TraceContext,
                 parent_span_id: str = "",
                 attrs: Optional[Dict] = None):
        self.trace_id = ctx.trace_id
        self.span_id = ctx.span_id
        self.parent_span_id = parent_span_id
        self.name = name
        self.start_ms = time_util.current_time_millis()
        self.duration_us = 0
        self.attrs: Dict = dict(attrs or {})
        self._t0 = time.perf_counter()

    def finish(self, duration_us: Optional[int] = None) -> "Span":
        """Stamp the duration (monotonic wall since construction, unless
        the caller measured it elsewhere — e.g. a server-shipped span)."""
        self.duration_us = (int((time.perf_counter() - self._t0) * 1e6)
                            if duration_us is None else int(duration_us))
        return self

    def to_dict(self) -> Dict:
        return {
            "traceId": self.trace_id,
            "spanId": self.span_id,
            "parentSpanId": self.parent_span_id,
            "name": self.name,
            "startMs": self.start_ms,
            "durationUs": self.duration_us,
            "attributes": dict(self.attrs),
        }


class SpanCollector:
    """Bounded host ring of finished spans + the sampling counter.

    ``sample()`` is the one dispatch-path call: a counter hit returns a
    fresh root :class:`TraceContext`, otherwise None — callers skip all
    span work on None, so the un-sampled steady state costs one integer
    op. Recording is lock-guarded appends of already-finished spans.
    """

    def __init__(self, sample_every: Optional[int] = None,
                 capacity: Optional[int] = None):
        from sentinel_tpu.core.config import (
            DEFAULT_TELEMETRY_SPANS_CAPACITY,
            DEFAULT_TELEMETRY_SPANS_SAMPLE_EVERY,
            TELEMETRY_SPANS_CAPACITY,
            TELEMETRY_SPANS_SAMPLE_EVERY,
            config as _cfg,
        )

        if sample_every is None:
            sample_every = _cfg.get_int(TELEMETRY_SPANS_SAMPLE_EVERY,
                                        DEFAULT_TELEMETRY_SPANS_SAMPLE_EVERY)
        if capacity is None:
            capacity = _cfg.get_int(TELEMETRY_SPANS_CAPACITY,
                                    DEFAULT_TELEMETRY_SPANS_CAPACITY)
        self.sample_every = max(0, int(sample_every))  # 0 = disabled
        self.capacity = max(1, int(capacity))
        self._lock = threading.Lock()
        self._ring: List[Dict] = []
        self._seen = 0
        self._recorded = 0

    def sample(self) -> Optional[TraceContext]:
        if self.sample_every <= 0:
            return None
        with self._lock:
            self._seen += 1
            if self._seen % self.sample_every != 0:
                return None
        return new_trace_context()

    def record(self, span: Span) -> None:
        d = span.to_dict()
        with self._lock:
            self._recorded += 1
            self._ring.append(d)
            del self._ring[:-self.capacity]

    def record_remote(self, ctx: TraceContext, name: str, parent_span_id: str,
                      start_ms: int, duration_us: int,
                      attrs: Optional[Dict] = None) -> None:
        """A span another process measured (e.g. the token server's,
        shipped back in the response TLV) joins the local ring verbatim."""
        with self._lock:
            self._recorded += 1
            self._ring.append({
                "traceId": ctx.trace_id, "spanId": ctx.span_id,
                "parentSpanId": parent_span_id, "name": name,
                "startMs": int(start_ms), "durationUs": int(duration_us),
                "attributes": dict(attrs or {}),
            })
            del self._ring[:-self.capacity]

    # -- read side --------------------------------------------------------

    def snapshot(self, limit: Optional[int] = None, offset: int = 0) -> Dict:
        from sentinel_tpu.telemetry.timeseries import page_newest_first

        with self._lock:
            spans = list(self._ring)
            seen, recorded = self._seen, self._recorded
        spans = page_newest_first(spans, limit, offset)
        spans.reverse()  # newest first
        return {
            "sampleEvery": self.sample_every,
            "capacity": self.capacity,
            "seen": seen,
            "recorded": recorded,
            "spans": spans,
        }

    def traces(self, limit: Optional[int] = None) -> List[Dict]:
        """Spans grouped per trace id, newest trace first."""
        with self._lock:
            spans = list(self._ring)
        grouped: Dict[str, List[Dict]] = {}
        order: List[str] = []
        for s in spans:
            if s["traceId"] not in grouped:
                order.append(s["traceId"])
            grouped.setdefault(s["traceId"], []).append(s)
        order.reverse()
        if limit is not None:
            order = order[:max(0, int(limit))]
        return [{"traceId": t, "spans": grouped[t]} for t in order]


def to_otlp(spans: List[Dict], service_name: str = "sentinel-tpu") -> Dict:
    """OTLP/JSON-flavored export of collected span dicts: the
    ``resourceSpans -> scopeSpans -> spans`` shape OTLP HTTP receivers
    and trace viewers ingest, with ns timestamps and typed attributes."""

    def _attrs(d: Dict) -> List[Dict]:
        out = []
        for k, v in d.items():
            if isinstance(v, bool):
                val = {"boolValue": v}
            elif isinstance(v, int):
                val = {"intValue": str(v)}
            elif isinstance(v, float):
                val = {"doubleValue": v}
            else:
                val = {"stringValue": str(v)}
            out.append({"key": str(k), "value": val})
        return out

    otlp_spans = []
    for s in spans:
        start_ns = int(s["startMs"]) * 1_000_000
        otlp_spans.append({
            "traceId": s["traceId"],
            "spanId": s["spanId"],
            "parentSpanId": s.get("parentSpanId", ""),
            "name": s["name"],
            "kind": 1,  # SPAN_KIND_INTERNAL
            "startTimeUnixNano": str(start_ns),
            "endTimeUnixNano": str(start_ns + int(s["durationUs"]) * 1000),
            "attributes": _attrs(s.get("attributes", {})),
        })
    return {
        "resourceSpans": [{
            "resource": {"attributes": _attrs({"service.name": service_name})},
            "scopeSpans": [{
                "scope": {"name": "sentinel_tpu.telemetry.spans"},
                "spans": otlp_spans,
            }],
        }],
    }

"""Event-loop command center (reference: ``sentinel-transport-netty-http``'s
``NettyHttpCommandCenter`` — SURVEY.md §2.3).

The reference ships TWO transports over one command-handler SPI: a
thread-per-connection simple-http server and a Netty event-loop server.
This is the event-loop twin of ``command_center.CommandCenter``: one
asyncio server task serves every connection (keep-alive supported), with
handler dispatch shared via :func:`~sentinel_tpu.transport.
command_center.dispatch_command` so the two transports cannot drift.

Two entry styles, mirroring how Netty servers get embedded:

  * sync apps: ``AsyncCommandCenter(engine).start()`` — spawns one daemon
    thread running a private event loop;
  * asyncio apps: ``await AsyncCommandCenter(engine).start_async()`` —
    serves on the caller's loop.
"""

from __future__ import annotations

import asyncio
import threading
from typing import Optional

from sentinel_tpu.core.config import config
from sentinel_tpu.transport.command_center import dispatch_command

_MAX_HEADER_BYTES = 64 * 1024
_MAX_BODY_BYTES = 4 * 1024 * 1024


class AsyncCommandCenter:
    def __init__(self, engine=None, port: Optional[int] = None,
                 host: Optional[str] = None):
        from sentinel_tpu.transport import handlers as _h  # noqa: F401

        self._engine = engine
        self.host = host or config.get("csp.sentinel.api.host") or "127.0.0.1"
        self.port = port if port is not None else config.api_port()
        self._server: Optional[asyncio.AbstractServer] = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._thread: Optional[threading.Thread] = None
        self._owns_loop = False

    @property
    def engine(self):
        if self._engine is not None:
            return self._engine
        import sentinel_tpu

        return sentinel_tpu.get_engine()

    @property
    def bound_port(self) -> int:
        if self._server is not None and self._server.sockets:
            return self._server.sockets[0].getsockname()[1]
        return self.port

    # -- connection handling ----------------------------------------------

    async def _serve_conn(self, reader: asyncio.StreamReader,
                          writer: asyncio.StreamWriter) -> None:
        try:
            while True:
                request = await reader.readline()
                if not request:
                    return
                try:
                    method, path, _version = request.decode(
                        "latin-1").strip().split(" ", 2)
                except ValueError:
                    return await self._respond(writer, 400, "bad request",
                                               close=True)
                headers = {}
                hdr_bytes = 0
                while True:
                    line = await reader.readline()
                    hdr_bytes += len(line)
                    if hdr_bytes > _MAX_HEADER_BYTES:
                        return await self._respond(writer, 431,
                                                   "headers too large",
                                                   close=True)
                    if line in (b"\r\n", b"\n", b""):
                        break
                    k, _, v = line.decode("latin-1").partition(":")
                    headers[k.strip().lower()] = v.strip()
                try:
                    length = int(headers.get("content-length") or 0)
                except ValueError:
                    return await self._respond(writer, 400,
                                               "bad content-length",
                                               close=True)
                if length < 0:
                    return await self._respond(writer, 400,
                                               "bad content-length",
                                               close=True)
                if length > _MAX_BODY_BYTES:
                    return await self._respond(writer, 413, "body too large",
                                               close=True)
                body = (await reader.readexactly(length)).decode("utf-8") \
                    if length else ""
                if method not in ("GET", "POST"):
                    await self._respond(writer, 405, "GET/POST only")
                    continue
                # Off-loop dispatch: a handler may recompile rules or block
                # on the engine lock for seconds — the event loop (possibly
                # the HOST app's loop under start_async) must keep serving.
                code, text, ctype = await asyncio.to_thread(
                    dispatch_command, self, path, body)
                keep = headers.get("connection", "keep-alive").lower() \
                    != "close"
                await self._respond(writer, code, text, close=not keep,
                                    ctype=ctype)
                if not keep:
                    return
        except (asyncio.IncompleteReadError, asyncio.LimitOverrunError,
                ConnectionError, ValueError):
            # ValueError: an oversized request line makes StreamReader's
            # readline raise it (limit exceeded) — drop the connection.
            pass
        finally:
            try:
                writer.close()
                await writer.wait_closed()
            except (ConnectionError, RuntimeError):
                pass

    async def _respond(self, writer: asyncio.StreamWriter, code: int,
                       text: str, close: bool = False,
                       ctype: str = "text/plain; charset=utf-8") -> None:
        reason = {200: "OK", 400: "Bad Request", 405: "Method Not Allowed",
                  413: "Payload Too Large", 431: "Headers Too Large",
                  500: "Internal Server Error"}.get(code, "Error")
        data = text.encode("utf-8")
        head = (f"HTTP/1.1 {code} {reason}\r\n"
                f"Content-Type: {ctype}\r\n"
                f"Content-Length: {len(data)}\r\n"
                f"Connection: {'close' if close else 'keep-alive'}\r\n"
                f"\r\n").encode("latin-1")
        writer.write(head + data)
        await writer.drain()

    # -- lifecycle ---------------------------------------------------------

    async def start_async(self) -> "AsyncCommandCenter":
        """Serve on the CURRENT event loop (asyncio-native apps)."""
        self._loop = asyncio.get_running_loop()
        self._server = await asyncio.start_server(
            self._serve_conn, self.host, self.port)
        return self

    async def stop_async(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None

    def start(self) -> "AsyncCommandCenter":
        """Spawn a daemon thread with a private loop (sync apps)."""
        ready = threading.Event()

        def run():
            loop = asyncio.new_event_loop()
            asyncio.set_event_loop(loop)
            self._loop = loop
            self._owns_loop = True

            async def boot():
                self._server = await asyncio.start_server(
                    self._serve_conn, self.host, self.port)
                ready.set()

            loop.run_until_complete(boot())
            try:
                loop.run_forever()
            finally:
                loop.run_until_complete(loop.shutdown_asyncgens())
                loop.close()

        self._thread = threading.Thread(
            target=run, name="sentinel-aio-command-center", daemon=True)
        self._thread.start()
        if not ready.wait(timeout=10):
            raise RuntimeError("async command center failed to start")
        return self

    def stop(self) -> None:
        loop, self._loop = self._loop, None
        if loop is None:
            return
        if self._owns_loop:
            async def shutdown():
                await self.stop_async()
                loop.stop()

            asyncio.run_coroutine_threadsafe(shutdown(), loop)
            if self._thread is not None:
                self._thread.join(timeout=5.0)
                self._thread = None
            return
        # start_async() on someone else's loop: stop() must still work —
        # silently returning would leak the bound listener for the process
        # lifetime. Off-loop callers get a synchronous close; on-loop
        # callers must await stop_async() (blocking here would deadlock).
        try:
            running = asyncio.get_running_loop()
        except RuntimeError:
            running = None
        if running is loop:
            self._loop = loop  # undo: the center is still live
            raise RuntimeError(
                "stop() called from the serving event loop; "
                "await stop_async() instead")
        asyncio.run_coroutine_threadsafe(self.stop_async(), loop).result(
            timeout=5.0)

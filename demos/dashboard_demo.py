"""Dashboard demo: engine + command center + heartbeat + metric log +
dashboard with live UI at http://127.0.0.1:8080/ — open it and watch the
pass/block chart while the traffic loop runs (Ctrl-C to stop)."""

import _demo_env  # noqa: F401

import os
import random
import tempfile
import time

os.environ.setdefault("CSP_SENTINEL_HEARTBEAT_CLIENT_IP", "127.0.0.1")
log_dir = tempfile.mkdtemp(prefix="sentinel-demo-logs-")
os.environ.setdefault("CSP_SENTINEL_LOG_DIR", log_dir)
os.environ.setdefault("PROJECT_NAME", "demo-app")

import sentinel_tpu as st
from sentinel_tpu.dashboard import DashboardServer
from sentinel_tpu.metrics.timer import MetricTimerListener
from sentinel_tpu.metrics.writer import MetricWriter
from sentinel_tpu.transport.command_center import CommandCenter
from sentinel_tpu.transport.heartbeat import HeartbeatSender

dash = DashboardServer(port=8080).start()
eng = st.get_engine()
center = CommandCenter(eng, port=0).start()
timer = MetricTimerListener(eng, MetricWriter(app="demo-app",
                                              base_dir=log_dir)).start()
hb = HeartbeatSender(dashboards=["127.0.0.1:8080"],
                     api_port=center.bound_port, interval_ms=5000).start()
hb.send_once()

st.load_flow_rules([st.FlowRule(resource="getUser", count=25),
                    st.FlowRule(resource="listOrders", count=8)])
print("dashboard: http://127.0.0.1:8080/  (Ctrl-C stops)")

try:
    while True:
        for res, n in (("getUser", random.randint(10, 35)),
                       ("listOrders", random.randint(3, 14))):
            for _ in range(n):
                h = st.entry_ok(res)
                if h:
                    h.exit()
        time.sleep(1.0)
except KeyboardInterrupt:
    pass

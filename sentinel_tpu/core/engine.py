"""The host engine: entry/exit API over the jitted device step.

This is the analog of the reference's ``CtSph`` + ``SphU`` (SURVEY.md §3.1):
it owns the node registry, the compiled rule tensors, the device state, and
the jitted ``entry_step`` / ``exit_step``; each ``entry()`` expands into a
micro-batch row, runs the step, and translates the decision into a pass,
a paced sleep, or a typed ``BlockException``.

Batch widths are drawn from a small fixed ladder so jit caches stay warm
(no dynamic shapes — XLA traces once per width). The synchronous path used
by the public API submits width-1 batches (correctness / low-rate callers);
high-rate callers and the bench use :meth:`check_batch` /
:meth:`complete_batch` directly, and the pipelined engine (M4) will feed
the same step functions from a background cadence loop.
"""

from __future__ import annotations

import functools
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from sentinel_tpu.core import constants as C
from sentinel_tpu.core import context as ctx_mod
from sentinel_tpu.core.batch import (
    BATCH_WIDTHS,
    Decisions,
    EntryBatch,
    ExitBatch,
    MAX_PARAMS,
    make_entry_batch_np,
    make_exit_batch_np,
)
from sentinel_tpu.core.exceptions import BlockException, exception_for_reason


class DeviceDispatchError(RuntimeError):
    """A device dispatch died (backend/tunnel failure) AFTER the input
    state may have been donated. The raising site has already dropped the
    engine to a cold state (reference restart stance: rules durable,
    stats ephemeral); catchers decide their own degradation — the sync
    entry path fails open, batch-API callers see the typed error."""
from sentinel_tpu.core.registry import NodeRegistry, ORIGIN_ID_NONE
from sentinel_tpu.metrics.profiling import StepTimer, timed_call
from sentinel_tpu.resilience import DeadlineBudget


class _FastPathState:
    """One atomically-swapped snapshot of the host fast-path config:
    entry() reads a single attribute, so a rule push can never expose a
    torn (leases, guarded, unruled) combination to a lock-free reader."""

    __slots__ = ("leases", "guarded", "unruled")

    def __init__(self, leases, guarded, unruled):
        self.leases = leases
        self.guarded = guarded
        self.unruled = unruled

from sentinel_tpu.models import authority as A
from sentinel_tpu.models import degrade as D
from sentinel_tpu.models import flow as F
from sentinel_tpu.models import param_flow as P
from sentinel_tpu.models import system as Y
from sentinel_tpu.ops import step as S
from sentinel_tpu.utils import time_util
from sentinel_tpu.utils.param_hash import hash_param as _hash_param

# Per-family slot-count floors at engine construction (and after a
# reset_slot_floor): flow starts at 1 (compile_flow_rules' historical
# floor); the rest compile to zero slots until first use. One definition
# shared by __init__ and reset_slot_floor so the two can't drift.
INITIAL_SLOT_FLOOR = {"flow": 1, "degrade": 0, "authority": 0, "param": 0}


class EntryHandle:
    """A live entry (reference: ``CtEntry``). Use as a context manager."""

    __slots__ = (
        "engine", "resource", "context", "cluster_row", "dn_row", "origin_row",
        "entry_in", "count", "created_ms", "error", "exited", "params",
        "leased", "slot_gen",
    )

    def __init__(self, engine, resource, context, cluster_row, dn_row,
                 origin_row, entry_in, count, params, leased=False,
                 now_ms=None):
        self.engine = engine
        self.resource = resource
        self.context = context
        self.cluster_row = cluster_row
        self.dn_row = dn_row
        self.origin_row = origin_row
        self.entry_in = entry_in
        self.count = count
        # Callers on the µs-scale fast path pass the clock they already
        # read; everyone else pays the (cached-tick) read here.
        self.created_ms = (engine.now_ms() if now_ms is None else now_ms)
        self.error = False
        self.exited = False
        self.params = params
        self.leased = leased
        # Slot-mode tenancy stamp (core/slots.py): the generation of the
        # slot this entry committed under, COLD_GEN (-2) for a cold-path
        # entry that must tally its exit host-side, -1 in fixed-capacity
        # mode / for pass-through handles.
        self.slot_gen = -1

    def trace(self, ex: Optional[BaseException] = None) -> None:
        """Record a business exception (reference: ``Tracer.trace``)."""
        if ex is None or not BlockException.is_block_exception(ex):
            self.error = True

    def exit(self, count: Optional[int] = None) -> None:
        if self.exited:
            return
        self.exited = True
        self.engine._do_exit(self, count if count is not None else self.count)

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc is not None and not BlockException.is_block_exception(exc):
            self.trace(exc)
        self.exit()
        return False


class SentinelEngine:
    """Owns device state + compiled rules; thread-safe via one lock.

    The device step itself is a pure function, so the lock only serializes
    host-side staging and the state-swap — the TPU analog of the reference's
    lock-free LeapArray updates is that *all* mutation happens inside one
    linearized step stream.
    """

    def __init__(self, capacity: int = 4096, clock=None,
                 journal_path: Optional[str] = None,
                 slot_budget: int = 0):
        # Clock-injection seam (ISSUE 13): every internal wall-clock read
        # goes through now_ms(), so a simulator can drive a REAL engine on
        # a program-advanced clock (sentinel_tpu/simulator/replay.py) with
        # no global freeze. None = the process clock (time_util, which
        # tests may freeze globally); a callable = this engine's private
        # timebase. The device step already takes ``now`` as an explicit
        # argument — this seam closes the host-side reads.
        self._clock = clock
        # Slot-table admission (core/slots.py — ROADMAP 1): slot_budget
        # > 0 (or csp.sentinel.slots.budget) bounds the DEVICE tensor to
        # ``budget`` rows and maps the live hot resource set into them
        # dynamically, with evict/rehydrate and a loud cold-tail degrade
        # past the budget. 0 = classic fixed-capacity mode, bit-for-bit
        # the pre-slot behavior. In slot mode the registry keeps a much
        # larger capacity for name interning + metadata (it no longer
        # sizes any device tensor); the device capacity IS the budget.
        from sentinel_tpu.core.config import config as _slots_cfg

        if not slot_budget:
            slot_budget = _slots_cfg.slots_budget()
        if slot_budget:
            from sentinel_tpu.core.slots import SlotTable

            self.registry = NodeRegistry(
                _slots_cfg.slots_registry_capacity())
            self.capacity = int(slot_budget)
            self.slots = SlotTable(self, int(slot_budget))
        else:
            self.registry = NodeRegistry(capacity)
            self.capacity = capacity
            self.slots = None
        # Instant-window geometry (reference: IntervalProperty /
        # SampleCountProperty — core:node/). Config-seeded, runtime-tunable
        # via set_window_geometry(); the minute window stays fixed (as
        # upstream's minute log does).
        from sentinel_tpu.core.config import config as _cfg
        from sentinel_tpu.ops import window as W_

        interval = _cfg.get_int("csp.sentinel.statistic.interval.ms",
                                C.SECOND_WINDOW_MS)
        samples = _cfg.get_int("csp.sentinel.statistic.sample.count",
                               C.SECOND_BUCKETS)
        if interval <= 0 or samples <= 0 or interval % samples != 0:
            # Same validation set_window_geometry enforces; a bad config
            # value must not brick boot (sample_count=0 would divide by
            # zero on the first rotate) — fall back to defaults, loudly.
            from sentinel_tpu.log.record_log import record_log

            record_log.warn("invalid csp.sentinel.statistic geometry "
                            "%sms/%s; using defaults", interval, samples)
            interval, samples = C.SECOND_WINDOW_MS, C.SECOND_BUCKETS
        self._spec1 = W_.WindowSpec(interval, samples)
        # Push-property form, like upstream's SampleCountProperty /
        # IntervalProperty (datasource-bindable):
        #   engine.window_geometry_property.update_value(
        #       {"intervalMs": 2000, "sampleCount": 4})
        from sentinel_tpu.core.property import (
            DynamicSentinelProperty, SimplePropertyListener)

        self.window_geometry_property = DynamicSentinelProperty()
        self.window_geometry_property.add_listener(SimplePropertyListener(
            lambda v: self.set_window_geometry(
                v.get("intervalMs"), v.get("sampleCount"))))
        # Prioritized-borrow wait cap (reference: OccupyTimeoutProperty —
        # core:node/). Config-seeded, runtime-tunable; push form:
        #   engine.occupy_timeout_property.update_value(250)
        seed_occupy = _cfg.get_int(
            "csp.sentinel.occupy.timeout.ms", C.DEFAULT_OCCUPY_TIMEOUT_MS)
        if not 0 <= seed_occupy <= interval:
            from sentinel_tpu.log.record_log import record_log

            record_log.warn(
                "invalid csp.sentinel.occupy.timeout.ms %s (window %sms); "
                "using default", seed_occupy, interval)
            seed_occupy = min(C.DEFAULT_OCCUPY_TIMEOUT_MS, interval)
        self._occupy_timeout_ms = seed_occupy
        self.occupy_timeout_property = DynamicSentinelProperty()
        self.occupy_timeout_property.add_listener(SimplePropertyListener(
            lambda v: self.set_occupy_timeout(int(v))))
        # Global kill switch (reference: Constants.ON via the setSwitch /
        # getSwitch command handlers). Off => every entry passes unguarded.
        self.enabled = True
        self.flow_rules = F.FlowRuleManager()
        self.flow_rules.add_listener(lambda: self._on_rules_changed("flow"))
        self.degrade_rules = D.DegradeRuleManager()
        self.degrade_rules.add_listener(lambda: self._mark_dirty("degrade"))
        self.authority_rules = A.AuthorityRuleManager()
        self.authority_rules.add_listener(lambda: self._mark_dirty("authority"))
        self.system_rules = Y.SystemRuleManager()
        self.system_rules.add_listener(lambda: self._mark_dirty("system"))
        self.param_rules = P.ParamFlowRuleManager()
        self.param_rules.add_listener(lambda: self._on_rules_changed("param"))
        # LLM admission (sentinel_tpu/llm/ — ISSUE 17): the TPS family
        # LOWERS onto flow rules (llm/rules.py) — the listener strips
        # previously-derived rules and re-injects, so the device machinery
        # gains no fourth tensor pack. The streaming-reservation ledger is
        # host-side, engine-timebase only, evicted on the spill cadence.
        from sentinel_tpu.llm.rules import TpsRuleManager
        from sentinel_tpu.llm.streams import StreamLedger

        self.tps_rules = TpsRuleManager()
        self.tps_rules.add_listener(self._on_tps_rules_changed)
        self._llm_max_streams: Dict[str, int] = {}
        self._llm_window_budget: Dict[str, float] = {}
        self._llm_default_estimate = _cfg.llm_default_estimate_tokens()
        self.streams = StreamLedger(
            capacity=_cfg.llm_max_streams(),
            idle_evict_ms=_cfg.llm_idle_evict_ms(),
            window_ms=interval)
        self.system_status = Y.SystemStatusListener()
        self._signals_refreshed_ms = 0
        self._sealed_sec = self.now_ms() // 1000 - 1
        # Control-plane audit journal (telemetry/journal.py — ISSUE 14):
        # every rule/SLO/target load, rollout transition, HA role flip,
        # shard-map apply, adaptive decision, and clock swap appends one
        # seq-numbered, causally-linked record. Constructed FIRST among
        # the observability surfaces: the rule managers, rollout, SLO,
        # adaptive, and cluster layers below all write through it (and
        # the SLO/adaptive logs RESTORE from it after a restart when a
        # file backs it). Stamps ride now_ms(), so a simulator replay
        # journals in simulated time. journal_path: None = the
        # csp.sentinel.journal.path config, "" = force memory-only
        # (the simulator's determinism stance — a shared file would
        # leak one replay's records into the next).
        from sentinel_tpu.telemetry.journal import ControlPlaneJournal

        self.journal = ControlPlaneJournal(self.now_ms, path=journal_path)
        # Fleet federation (telemetry/fleet.py): a FleetView collector
        # attached via the `fleet` ops command (None = not watching).
        self.fleet = None
        # Flight-recorder tee (ISSUE 13): callables invoked with each
        # freshly spilled complete second, already rendered to the
        # ``second_to_dict`` JSON shape — the trace writer subscribes
        # here (simulator/trace.py) so live traffic can be captured into
        # a portable replay trace with zero extra device work.
        self._flight_tees: List = []
        # Cluster role (client / embedded server) — host-side maps from
        # resource to its cluster-mode rules' (flowId, fallbackToLocal).
        from sentinel_tpu.cluster.state import ClusterStateManager

        self.cluster = ClusterStateManager()
        # Role flips (ops setClusterMode, HA promotions) journal through
        # the owning engine — and servers the manager starts serve THIS
        # engine's bridge + fleet telemetry; standalone managers leave
        # both None.
        self.cluster.journal = self.journal
        self.cluster.engine = self
        # Staged rollout (sentinel_tpu/rollout/): candidate rulesets
        # evaluated in shadow lanes of the fused step, optionally enforced
        # for a deterministic canary slice. The compiled candidate pack +
        # the traced canary scalars live here; the manager owns lifecycle
        # and guardrails. Constructed AFTER the rule managers (it reads
        # their staged partitions) but BEFORE any listener can fire.
        self._shadow_rules: Optional[S.RulePack] = None
        self._canary_bps: Optional[int] = None
        self._canary_salt = 0
        from sentinel_tpu.rollout.manager import RolloutManager

        self.rollout = RolloutManager(self)
        self._cluster_flow_info: Dict[str, list] = {}
        self._cluster_param_info: Dict[str, list] = {}
        # flowId -> (threshold, windowIntervalMs) of the LOCAL copies of
        # cluster-mode flow rules: the HA client's degraded-quota share
        # base (cluster/ha.py — per-client share of the global threshold
        # while no leader is reachable). Replaced wholesale on rule load.
        self._cluster_thresholds: Dict[int, tuple] = {}
        self._pipeline = None
        # Cumulative pipelined-admission counters across pipeline
        # start/stop generations (the live Pipeline object dies with
        # stop_pipeline; scrapers need monotone counters).
        self._pipeline_totals = {
            "cycles": 0, "batched": 0, "harvests": 0, "failOpenCycles": 0,
            "inflightDepthMax": 0, "poolAllocated": 0, "poolReused": 0,
        }
        # Guards the totals fold + the retiring hand-off so scrapes
        # during stop_pipeline() never see the monotone counters dip
        # (and concurrent stops can never double-fold). Deliberately
        # NOT the engine lock: stats reads must not stall behind a
        # dispatch-held compile.
        self._pipeline_stats_lock = threading.Lock()
        # A pipeline between "unhooked from admission" and "counters
        # folded" — pipeline_stats() keeps reading its live counters.
        self._retiring_pipeline = None
        # Entries that passed UNGUARDED because the pipeline could not
        # produce a verdict (collector death / cycle error). A silent
        # fail-open is an invisible protection outage — count it and log
        # at most once per second (reference's fallback is at least
        # observable through block logs).
        self.fail_open_count = 0
        self._fail_open_logged_ms = 0
        # Resilience accounting (sentinel_tpu/resilience/): how often
        # cluster-mode rules degraded to their local fallback, and the
        # aggregate remote-wait budget one entry() may spend in
        # _cluster_token_check (bounded-latency graceful degradation —
        # the old behavior paid up to request_timeout_s PER cluster rule
        # plus unbounded SHOULD_WAIT sleeps).
        self.cluster_fallback_count = 0
        self.cluster_budget_exhausted_count = 0
        # Overload sheds (ISSUE 6): entries whose cluster check came back
        # OVERLOADED (the token server shed before admission) and were
        # served via the local lease/fallback path instead.
        self.cluster_overload_count = 0
        # Shard mis-routes (ISSUE 12): entries whose cluster check came
        # back WRONG_SLICE un-healed — the client's routing map was
        # stale past what the self-healing walk could absorb (or a
        # plain unsharded client is pointed at a sharded leader); the
        # rule degraded to its local fallback.
        self.cluster_wrong_slice_count = 0
        from sentinel_tpu.core.config import (
            DEFAULT_RESILIENCE_ENTRY_BUDGET_MS, RESILIENCE_ENTRY_BUDGET_MS)

        self.cluster_entry_budget_ms = _cfg.get_int(
            RESILIENCE_ENTRY_BUDGET_MS, DEFAULT_RESILIENCE_ENTRY_BUDGET_MS)
        if self.cluster_entry_budget_ms <= 0:
            from sentinel_tpu.log.record_log import record_log

            record_log.warn("invalid %s=%s; using default %dms",
                            RESILIENCE_ENTRY_BUDGET_MS,
                            self.cluster_entry_budget_ms,
                            DEFAULT_RESILIENCE_ENTRY_BUDGET_MS)
            self.cluster_entry_budget_ms = DEFAULT_RESILIENCE_ENTRY_BUDGET_MS
        # Per-step timing (SURVEY §5): enqueue wall per dispatch + sampled
        # synchronous step wall; surfaced via the `profile` ops command.
        # The sampling cadence is config-tunable (`csp.sentinel.profile.
        # syncEvery`): every Nth dispatch blocks for a true step wall.
        from sentinel_tpu.core.config import (
            DEFAULT_PROFILE_SYNC_EVERY, PROFILE_SYNC_EVERY)

        sync_every = _cfg.get_int(PROFILE_SYNC_EVERY,
                                  DEFAULT_PROFILE_SYNC_EVERY)
        if sync_every <= 0:
            from sentinel_tpu.log.record_log import record_log

            record_log.warn("invalid %s=%s; using default %d",
                            PROFILE_SYNC_EVERY, sync_every,
                            DEFAULT_PROFILE_SYNC_EVERY)
            sync_every = DEFAULT_PROFILE_SYNC_EVERY
        self.step_timer = StepTimer(sync_every=sync_every)
        # Sampled decision traces (sentinel_tpu/telemetry/): every Nth
        # blocked entry pulled off-device asynchronously, served by the
        # `traces` ops command and the dashboard.
        from sentinel_tpu.telemetry.trace_ring import DecisionTraceBuffer

        self.traces = DecisionTraceBuffer(self)
        # Cross-process spans (telemetry/spans.py): every Nth cluster-
        # checked entry carries a trace context over the token-server
        # wire; the stitched spans land here for the `traces` command's
        # span view and the OTLP export.
        from sentinel_tpu.telemetry.spans import SpanCollector

        self.spans = SpanCollector()
        # Flight recorder (telemetry/timeseries.py): device ring length
        # (0 disables the device tensors entirely) + the compacted
        # host-side history the ring spills into on reads.
        from sentinel_tpu.core.config import (
            DEFAULT_TELEMETRY_TIMESERIES_HISTORY,
            DEFAULT_TELEMETRY_TIMESERIES_SECONDS,
            TELEMETRY_TIMESERIES_HISTORY,
            TELEMETRY_TIMESERIES_SECONDS,
        )
        from sentinel_tpu.telemetry.timeseries import TimeseriesHistory

        self.flight_seconds = max(0, _cfg.get_int(
            TELEMETRY_TIMESERIES_SECONDS,
            DEFAULT_TELEMETRY_TIMESERIES_SECONDS))
        self.timeseries = TimeseriesHistory(_cfg.get_int(
            TELEMETRY_TIMESERIES_HISTORY,
            DEFAULT_TELEMETRY_TIMESERIES_HISTORY))
        # SLO engine (sentinel_tpu/slo/): burn-rate objectives + anomaly
        # baselines + health scores, evaluated from the COMPLETE seconds
        # the flight recorder spills — fed by _spill_flight, so the
        # judgement layer rides the existing once-per-second fold and
        # adds zero per-step device work.
        from sentinel_tpu.slo.manager import SloManager

        self.slo = SloManager(self)
        # Wire-to-device latency waterfall (ISSUE 18): per-stage log2
        # histograms over perf_counter stage deltas, sealed once per
        # second by _spill_flight's fold. Constructed AFTER slo — its
        # regression sentry fires through slo.external_transition.
        from sentinel_tpu.telemetry.waterfall import WaterfallRecorder

        self.waterfall = WaterfallRecorder(self)
        # Namespace telescope (ISSUE 19): population sensing over the
        # unbounded (resource, flowId) key space — top-k / CMS / HLL /
        # churn riding the same spill fold. Constructed AFTER slo for
        # the same reason as the waterfall: its cardinality alarm fires
        # through slo.external_transition.
        from sentinel_tpu.telemetry.population import PopulationTracker

        self.population = PopulationTracker(self)
        # Closed-loop adaptive limiting (sentinel_tpu/adaptive/): the
        # acting half of the loop the SLO engine senses for. Constructed
        # AFTER rollout (it registers a lifecycle listener) and slo (its
        # senses read judgement); ticks ride _spill_flight, so the loop
        # adds zero per-step device work and no background thread.
        from sentinel_tpu.adaptive.loop import AdaptiveLoop

        self.adaptive = AdaptiveLoop(self)
        # Governed shard placement (ISSUE 16): senses the fleet plane,
        # proposes minimal-movement map diffs, chaos-certifies them, and
        # applies through the journal-audited HA path. Pure control
        # plane — no background thread; ops drive it via `rebalance`.
        from sentinel_tpu.cluster.rebalance import ShardRebalancer

        self.rebalancer = ShardRebalancer(self)
        # Token-lease fast path (core/lease.py): host-admitted resources +
        # the async stats committer. Rebuilt on every rule push.
        self.lease_enabled = (
            (_cfg.get("csp.sentinel.lease.enabled") or "true").lower()
            != "false")
        # Unruled resources may skip the device check entirely (always
        # pass + async stats commit); flipped off with system rules / SPI.
        self._fastpath = _FastPathState({}, frozenset(), self.lease_enabled)
        self._committer = None
        self._closed = False
        self._lock = threading.RLock()
        # Config-plane lock: serializes rule pushes / geometry retunes /
        # close against EACH OTHER without making them wait on the device
        # dispatch path, which holds ``_lock`` for the full XLA call —
        # including first-dispatch compiles (seconds on CPU, 20-40s on
        # TPU). Before the split, a rule push racing a cold compile
        # appeared to "not take": the manager had the new rules while the
        # lease table served the old thresholds until the compile
        # finished. Lock ORDER is config -> engine; never acquire
        # ``_config_lock`` while holding ``_lock``.
        self._config_lock = threading.RLock()
        self._state: Optional[S.SentinelState] = None
        self._rules: Optional[S.RulePack] = None
        self._named_origins: Dict[str, set] = {}
        self._dirty = {"flow": True, "degrade": True, "authority": True,
                       "system": True, "param": True, "rollout": False}
        # Slot-count ratchet per family: empty families compile to ZERO
        # slots (their per-slot loops vanish — a no-rules step is ~4x
        # cheaper), but 0 -> 1 slots is a tensor-SHAPE change that would
        # retrace the fused step on a rule push. Flooring each compile at
        # the widest slot count ever seen keeps the round-4 guarantee
        # "rule pushes don't recompile" for every push after a family's
        # first use (the first-use retrace is one-time and unavoidable).
        # Flow starts at 1 (compile_flow_rules' historical floor) and
        # ratchets up the same way: a second rule on one resource widens
        # the shape once and it never shrinks back.
        self._slot_floor = dict(INITIAL_SLOT_FLOOR)
        self._rebuild_w1_jits()
        self._flush_jit = jax.jit(S.flush_seconds, donate_argnums=(0,))
        self._w60_read_jit = jax.jit(lambda st_, now, idx: jnp.transpose(
            W_.rotate(st_.w60, now, S.SPEC_60S).counts[idx], (2, 0, 1)))
        # Flight-recorder spill read: gather only the requested ring
        # slots on device, ONE host transfer (full-ring reads would move
        # the whole ~55MB ring per spill).
        self._flight_read_jit = jax.jit(lambda st_, idx: (
            st_.flight.events[idx], st_.flight.attr[idx],
            st_.flight.hist[idx], st_.flight.slot_attr[idx]))
        # SPI boot (reference: Env static init -> InitExecutor.doInit) +
        # device-checker splice: the step re-jits when registrations change.
        from sentinel_tpu.core import spi as spi_mod

        self._spi = spi_mod
        self._spi_version = -1
        self._entry_jit = None
        self._rebuild_entry_jit()
        # Init funcs do NOT run here: an @init_func calling the module API
        # mid-construction would hit a half-assigned singleton. get_engine()
        # fires them once the default engine is installed (the reference's
        # "first SphU.entry triggers doInit" ordering).

    # -- clock seam (ISSUE 13) ---------------------------------------------

    def now_ms(self) -> int:
        """This engine's timebase: the injected clock when one is set
        (simulator replay), else the process clock (which tests freeze
        globally via time_util). Every host-side time read inside the
        engine — and in the adaptive/rollout/SLO layers riding it — goes
        through here, so a replayed engine experiences ONE consistent,
        program-advanced time."""
        clock = self._clock
        return clock() if clock is not None else \
            time_util.current_time_millis()

    def set_clock(self, clock) -> None:
        """Install (or clear, with None) an injected clock, resetting
        the engine's time cursors AND its volatile statistics to the
        new timebase.

        The cursors assume time never moves backward: ``_sealed_sec``
        gates the metric log, ``timeseries.last_stamp_ms`` gates the
        flight-recorder spill, and the signal/log throttles hold
        last-read stamps. Swapping to a timebase earlier than the old
        one would otherwise silently wedge all of them (seconds "already
        sealed/spilled", throttles never expiring) — the latent
        real-time-monotonicity assumption this seam flushes out. Device
        state is dropped cold for the same reason: window bucket
        starts, the staged second, and flight-ring slots all carry
        old-timebase stamps that would interleave wrongly with the new
        one. Rules survive, statistics restart — the reference restart
        stance, rebuilt on the next dispatch (shape-cached jits make
        that a cheap ``make_state``, not a recompile)."""
        with self._config_lock, self._lock:
            self._clock = clock
            now = self.now_ms()
            self._sealed_sec = now // 1000 - 1
            self._signals_refreshed_ms = 0
            self._fail_open_logged_ms = 0
            self._state = None  # stats ephemeral; _ensure_compiled rebuilds
            self.timeseries.clear()
            # Lease mirrors carry last-filled / window stamps of the OLD
            # timebase: a warm-up mirror with a future-stamped sync (or a
            # param bucket that can never refill) would wedge the fast
            # path exactly like the spill cursors above. Drop the table
            # and rebuild COLD — swapping the fast path to empty first
            # keeps _rebuild_leases from carrying the stale mirrors over
            # (its carry-over exists for rule pushes, where the timebase
            # is continuous).
            self._fastpath = _FastPathState({}, frozenset(),
                                            self.lease_enabled)
            self._rebuild_leases()
        # Stamp-bearing subsystem cursors reset OUTSIDE the engine locks
        # (they take their own locks, and the established order is
        # adaptive/slo -> engine, never the inverse): SLO ingest/eval
        # cursors + series/baselines/alerts, and the adaptive loop's
        # abort backoff + envelope cooldown stamps — all absolute times
        # of the old timebase that would wedge judgement or freeze
        # retuning for (simulated) decades after a backward swap.
        self.slo.reset_timebase()
        adaptive = getattr(self, "adaptive", None)
        if adaptive is not None:
            adaptive.reset_timebase()
        rebalancer = getattr(self, "rebalancer", None)
        if rebalancer is not None:
            rebalancer.reset_timebase()
        waterfall = getattr(self, "waterfall", None)
        if waterfall is not None:
            waterfall.reset_timebase()
        population = getattr(self, "population", None)
        if population is not None:
            population.reset_timebase()
        # Audit the swap itself — stamped with the NEW timebase (the
        # old one no longer exists to stamp with). seq stays monotone
        # across the swap even though timestamps may step backward;
        # SEMANTICS.md "Journal causality" names this asymmetry.
        self.journal.record("clockSwap", injected=clock is not None)

    def add_flight_tee(self, fn) -> None:
        """Subscribe ``fn(second_dict)`` to every freshly spilled
        complete flight-recorder second (the trace-capture hook)."""
        self._flight_tees.append(fn)

    def remove_flight_tee(self, fn) -> None:
        try:
            self._flight_tees.remove(fn)
        except ValueError:
            pass

    @property
    def _leases(self):
        return self._fastpath.leases

    @property
    def _guarded_resources(self):
        return self._fastpath.guarded

    @property
    def _unruled_fastpath(self):
        return self._fastpath.unruled

    def _rebuild_leases(self) -> None:
        """Recompute the token-lease table from current rules + geometry.

        Mirrors must NOT reset to zero on a rule push — re-granting quota
        already spent this window would double-admit. Surviving resources
        carry their mirror over; newly-eligible ones seed from the device
        window (their past traffic took the device path, so the window IS
        their usage)."""
        from sentinel_tpu.core.lease import build_lease_table

        if self._closed:
            # close() swapped in the empty fast path; a straggler push
            # must not resurrect lease admission on a closed engine.
            return
        old = self._leases
        if self.lease_enabled:
            new, guarded, unruled_ok = build_lease_table(self)
        else:
            new, guarded, unruled_ok = {}, set(), False
        fresh = []
        for res, lease in new.items():
            prev = old.get(res)
            if prev is not None and prev.buckets == lease.buckets \
                    and prev.bucket_ms == lease.bucket_ms:
                lease.seed(*prev.snapshot())
            else:
                fresh.append(res)
        if fresh:
            self._seed_leases_into(new, fresh)
        self._fastpath = _FastPathState(new, guarded, unruled_ok)

    def _ensure_committer(self):
        committer = self._committer
        if committer is None:
            from sentinel_tpu.core.lease import StatsCommitter, SyncCommitter

            with self._lock:
                if self._closed:
                    # An entry racing close() read the fast path before the
                    # swap; committing inline beats silently resurrecting a
                    # daemon thread (+hooks) on a closed engine.
                    return SyncCommitter(self)
                if self._committer is None:
                    self._committer = StatsCommitter(self).start()
                committer = self._committer
        return committer

    def _flush_committer(self) -> None:
        """Drain pending leased commits so reads are deterministic."""
        committer = self._committer
        if committer is not None:
            committer.flush()

    def _seed_leases_from_state(self, only: Optional[List[str]] = None) -> None:
        """Adopt device windows into the lease mirrors (checkpoint warm
        restart)."""
        targets = [res for res in self._leases
                   if only is None or res in only]
        self._seed_leases_into(self._leases, targets)

    def _seed_leases_into(self, table, targets) -> None:
        """Seed ``targets``' mirrors in ``table`` from the device window
        PLUS any un-flushed committer commits (a previously-unruled
        resource's recent traffic may still sit in the queue; flushing
        here would deadlock against the background flush, which takes the
        engine lock we may already hold — so count, don't flush).

        Row lookup is NON-allocating: a resource with no registry row has
        never served traffic, so there is nothing to seed (and allocating
        here would make a mere rule load consume rows, tripping
        ``restore_checkpoint``'s fresh-engine guard)."""
        targets = [res for res in targets if res in table]
        if not targets:
            return
        with self._lock:
            state = self._state
            if state is not None:
                pass_counts = np.asarray(
                    state.w1.counts[:, C.MetricEvent.PASS, :])
                starts = np.asarray(state.w1.starts)
            rows = {}
            for res in targets:
                row = self._device_row_of(res)
                if row is not None:
                    rows[res] = row
        committer = self._committer
        pending = committer.pending_pass_counts() if committer else {}
        now = self.now_ms()
        for res in targets:
            if res not in rows:
                continue  # never served traffic: mirror stays empty
            lease = table[res]
            if state is not None:
                lease.seed(starts, pass_counts[:, rows[res]])
            # Queued (not yet flushed) commits are real usage too — with no
            # device state yet (nothing ever flushed) they are ALL of it.
            queued = pending.get(rows[res], 0)
            if queued:
                lease.add(queued, now)

    def _rebuild_w1_jits(self):
        """(Re)build the spec1-dependent jits — one construction site shared
        by __init__ and set_window_geometry, so a retuned engine cannot
        drift from boot behavior.

        Jitted read paths: unjitted window rotation dispatches op-by-op and
        measured ~100ms/read at 32k rows; one compiled program is ~1ms (see
        seal_metrics docstring for the 10k-resource numbers). The totals
        read normalizes window sums to per-second QPS (reference
        ``StatisticNode.passQps`` divides by the interval in seconds), the
        same scaling the flow checker applies on-device.
        """
        from sentinel_tpu.ops import window as W_

        spec1 = self._spec1
        qps_scale = jnp.float32(1000.0 / spec1.interval_ms)
        self._exit_jit = jax.jit(
            functools.partial(S.exit_step, spec1=spec1), donate_argnums=(0,))
        self._w1_read_jit = jax.jit(lambda st_, now: (
            W_.all_totals(W_.rotate(st_.w1, now, spec1)).astype(jnp.float32)
            * qps_scale,
            st_.cur_threads))

    def _rebuild_entry_jit(self):
        # Version BEFORE checkers: a registration racing between the two
        # reads then leaves version != snapshot and the next
        # _ensure_compiled re-runs this (the reverse order would pin a
        # stale checker set forever).
        self._spi_version = self._spi.device_version()
        checkers = self._spi.device_checkers()
        step = functools.partial(
            S.entry_step, extra_checkers=checkers, spec1=self._spec1)
        self._entry_jit = jax.jit(step, donate_argnums=(0,))

    # -- rule compilation --------------------------------------------------

    def _mark_dirty(self, family: str):
        # Config lock, NOT the engine lock: the dirty flag hand-off is a
        # GIL-atomic dict write (_ensure_compiled reads it under the
        # engine lock on next dispatch), and the lease rebuild must not
        # queue behind an in-flight dispatch's compile (see _config_lock).
        with self._config_lock:
            self._dirty[family] = True
            self._sync_rollout_sources()
            self._rebuild_leases()
        self._slots_sync_pins()
        self._journal_rule_load(family)

    def _journal_rule_load(self, family: str) -> None:
        """One ``ruleLoad`` audit record per family load: who pushed
        (the ``acting()`` provenance context — datasource pollers and
        ops commands set it), what is now in force (rule dicts, capped),
        and what caused it (a rollout promotion's ``causing()`` seam).
        Runs OUTSIDE the config lock — the journal fsync must never
        extend the window a rule push holds the config plane."""
        from sentinel_tpu.datasource import converters as CV
        from sentinel_tpu.telemetry.journal import MAX_RULES_PER_RECORD

        mgr, to_dict = {
            "flow": (self.flow_rules, CV.flow_rule_to_dict),
            "degrade": (self.degrade_rules, CV.degrade_rule_to_dict),
            "authority": (self.authority_rules, CV.authority_rule_to_dict),
            "system": (self.system_rules, CV.system_rule_to_dict),
            "param": (self.param_rules, CV.param_rule_to_dict),
            "tps": (self.tps_rules, CV.tps_rule_to_dict),
        }[family]
        rules = list(mgr.get_rules())
        dicts = []
        for r in rules[:MAX_RULES_PER_RECORD]:
            try:
                dicts.append(to_dict(r))
            except Exception:  # noqa: BLE001 — audit must not break loads
                dicts.append({"resource": getattr(r, "resource", None)})
        self.journal.record(
            "ruleLoad", family=family, count=len(rules), rules=dicts,
            rulesTruncated=len(rules) > MAX_RULES_PER_RECORD)

    def _sync_rollout_sources(self) -> None:
        """Rule pushes may carry staged (candidate-tagged) rules, and the
        active candidate's MERGED view depends on the live rules — both
        make the compiled shadow pack stale. Caller holds the config lock."""
        rollout = getattr(self, "rollout", None)
        if rollout is None:
            return
        rollout.refresh_staged()
        if rollout.device_active():
            self._dirty["rollout"] = True

    def _set_canary(self, bps: Optional[int], salt: int) -> None:
        """Canary knobs are TRACED step scalars: tuning the percentage or
        salt never recompiles; only the None<->set flip (enter/leave the
        canary stage) retraces, like any argument-structure change."""
        self._canary_bps = None if bps is None else int(bps)
        self._canary_salt = int(salt)

    def _on_rules_changed(self, family: str):
        """Flow/param loads also rebuild the host-side cluster-rule maps
        eagerly (cheap scans), so the entry() fast path can consult them
        lock-free: the dicts are replaced wholesale, never mutated."""
        with self._config_lock:
            self._dirty[family] = True
            self._sync_rollout_sources()
            self._rebuild_leases()
            if family == "flow":
                rules = self.flow_rules.get_rules()
                self._cluster_flow_info = self._cluster_info(rules)
                self._cluster_thresholds = self._cluster_threshold_map(rules)
                # origin_named is read on entry BEFORE compilation runs, so
                # the named-origin map must be fresh at load time too (same
                # classification helper as the compiler — no drift).
                self._named_origins = F.named_origin_map(rules, self.registry)
            else:
                self._cluster_param_info = self._cluster_info(
                    self.param_rules.get_rules(), with_param_idx=True)
        self._slots_sync_pins()
        self._journal_rule_load(family)

    def _on_tps_rules_changed(self):
        """TPS loads LOWER onto the flow family (llm/rules.py): strip the
        previously-derived rules, re-inject the fresh lowering, keep every
        operator rule (live and staged) untouched. The flow load below
        fires the normal flow listener, so tensors/leases/cluster maps
        rebuild with no TPS-specific compilation path. An operator flow
        push replaces the whole flow list — lowered rules vanish until
        the next TPS load re-lowers (documented contract)."""
        from sentinel_tpu.llm import rules as LR

        tps_live = self.tps_rules.get_rules()
        tps_staged = [r for rs in self.tps_rules.get_staged().values()
                      for r in rs]
        lowered = LR.lower_tps_rules(tps_live) \
            + LR.lower_tps_rules(tps_staged)
        # Replaced wholesale, never mutated — entry()'s stream_open
        # concurrency check reads it lock-free.
        self._llm_max_streams = LR.max_streams_by_resource(tps_live)
        # resource -> tightest per-window token budget: the reservation
        # cap (an up-front reservation can never exceed one window's
        # budget — the rest of a long generation pays live as it
        # streams across later windows).
        budgets: Dict[str, float] = {}
        for r in LR.lower_tps_rules(tps_live):
            cur = budgets.get(r.resource)
            budgets[r.resource] = r.count if cur is None \
                else min(cur, r.count)
        self._llm_window_budget = budgets
        keep = [r for r in self.flow_rules.get_rules()
                if getattr(r, "derived_from", None) != LR.DERIVED_TPS]
        keep += [r for rs in self.flow_rules.get_staged().values()
                 for r in rs
                 if getattr(r, "derived_from", None) != LR.DERIVED_TPS]
        self.flow_rules.load_rules(keep + lowered)
        self._journal_rule_load("tps")

    def _ensure_compiled(self):
        """(Re)build rule tensors + state after a config push (§3.2).

        Each family rebuilds independently: a flow-rule push re-creates
        flow controller state (reference: "WarmUp state re-created!") but
        leaves circuit-breaker state intact, and vice versa. Node stats
        always survive.
        """
        if self._spi_version != self._spi.device_version():
            self._rebuild_entry_jit()  # SPI device checker set changed
        # Dirty flags are cleared BEFORE the corresponding get_rules()
        # read, and the dict object is never rebound: rule pushes set the
        # flag on the config plane WITHOUT the engine lock (_mark_dirty),
        # so clear-after-read would lose a push landing mid-compile (the
        # dispatcher would clear a flag it never compiled for, and the
        # device tensors would enforce stale rules until an unrelated
        # later push). Clear-first at worst costs one redundant recompile.
        if self._state is None:
            for k in self._dirty:
                self._dirty[k] = False
            now = self.now_ms()
            ft, named = F.compile_flow_rules(
                self.flow_rules.get_rules(), self._rule_registry(),
                self.capacity, min_slots=self._slot_floor["flow"])
            dt, di = D.compile_degrade_rules(
                self.degrade_rules.get_rules(), self._rule_registry(),
                self.capacity, min_slots=self._slot_floor["degrade"])
            pt = P.compile_param_rules(
                self.param_rules.get_rules(), self._rule_registry(),
                self.capacity, min_slots=self._slot_floor["param"])
            at = A.compile_authority_rules(
                self.authority_rules.get_rules(), self._rule_registry(),
                self.capacity, min_slots=self._slot_floor["authority"])
            self._ratchet_slots(flow=ft, degrade=dt, param=pt, authority=at)
            self._named_origins = {r: set(o) for r, o in named.items()}
            self._rules = S.RulePack(
                flow=ft, degrade=dt, authority=at,
                system=Y.compile_system_rules(self.system_rules.get_rules()),
                param=pt,
            )
            self._state = S.make_state(self.capacity, ft.num_rules, now,
                                       degrade=D.make_degrade_state(dt, di),
                                       param=P.make_param_state(pt.num_rules),
                                       spec1=self._spec1,
                                       flight_seconds=self.flight_seconds)
            self._maybe_start_system_listener()
            self._compile_shadow()
            return
        if not any(self._dirty.values()):
            return
        now = self.now_ms()
        if self._dirty["flow"]:
            self._dirty["flow"] = False
            ft, named = F.compile_flow_rules(
                self.flow_rules.get_rules(), self._rule_registry(),
                self.capacity, min_slots=self._slot_floor["flow"])
            self._ratchet_slots(flow=ft)
            self._named_origins = {r: set(o) for r, o in named.items()}
            self._rules = self._rules._replace(flow=ft)
            self._state = self._state._replace(flow=F.make_flow_state(ft.num_rules, now))
        if self._dirty["degrade"]:
            self._dirty["degrade"] = False
            dt, di = D.compile_degrade_rules(
                self.degrade_rules.get_rules(), self._rule_registry(),
                self.capacity, min_slots=self._slot_floor["degrade"])
            self._ratchet_slots(degrade=dt)
            self._rules = self._rules._replace(degrade=dt)
            self._state = self._state._replace(degrade=D.make_degrade_state(dt, di))
        if self._dirty["authority"]:
            self._dirty["authority"] = False
            at = A.compile_authority_rules(
                self.authority_rules.get_rules(), self._rule_registry(),
                self.capacity, min_slots=self._slot_floor["authority"])
            self._ratchet_slots(authority=at)
            self._rules = self._rules._replace(authority=at)
        if self._dirty["system"]:
            self._dirty["system"] = False
            self._rules = self._rules._replace(
                system=Y.compile_system_rules(self.system_rules.get_rules()))
            self._maybe_start_system_listener()
        if self._dirty["param"]:
            self._dirty["param"] = False
            pt = P.compile_param_rules(
                self.param_rules.get_rules(), self._rule_registry(),
                self.capacity, min_slots=self._slot_floor["param"])
            self._ratchet_slots(param=pt)
            self._rules = self._rules._replace(param=pt)
            self._state = self._state._replace(param=P.make_param_state(pt.num_rules))
        if self._dirty["rollout"]:
            self._dirty["rollout"] = False
            self._compile_shadow()

    def _compile_shadow(self) -> None:
        """(Re)build the candidate pack + a fresh shadow world, or tear
        both down when no candidate holds the device.

        The candidate compiles from the MERGED view (live rules plus the
        candidate's per-resource overrides — rollout/manager.py), with the
        same slot floors as the live pack so the common candidate-close-
        to-live case shares tensor shapes. Installing/removing a shadow is
        a state-STRUCTURE change: one retrace, like a family's first use.
        Like a live rule load, a candidate edit re-creates controller
        state — the shadow world (and its counters) restarts cold; the
        rollout guardrail re-baselines on its next tick.
        """
        self._dirty["rollout"] = False
        rollout = getattr(self, "rollout", None)
        spec = rollout.device_spec() if rollout is not None else None
        if spec is None:
            self._shadow_rules = None
            if self._state is not None and self._state.shadow is not None:
                self._state = self._state._replace(shadow=None)
            return
        ft, _ = F.compile_flow_rules(
            spec["flow"], self._rule_registry(), self.capacity,
            min_slots=self._slot_floor["flow"])
        dt, di = D.compile_degrade_rules(
            spec["degrade"], self._rule_registry(), self.capacity,
            min_slots=self._slot_floor["degrade"])
        at = A.compile_authority_rules(
            spec["authority"], self._rule_registry(), self.capacity,
            min_slots=self._slot_floor["authority"])
        pt = P.compile_param_rules(
            spec["param"], self._rule_registry(), self.capacity,
            min_slots=self._slot_floor["param"])
        self._shadow_rules = S.RulePack(
            flow=ft, degrade=dt, authority=at,
            system=Y.compile_system_rules(spec["system"]), param=pt)
        if self._state is not None:
            self._state = self._state._replace(shadow=S.make_shadow_state(
                self.capacity, self._shadow_rules,
                D.make_degrade_state(dt, di), spec1=self._spec1))

    def _ratchet_slots(self, **tensors) -> None:
        """Raise each family's slot floor to what was just compiled, so
        later pushes (even back to zero rules) keep the same tensor
        shapes and never retrace the fused step.

        The ratchet is monotonic for the process lifetime BY DESIGN: a
        one-time burst of K rules on one resource widens that family's
        per-slot device loop to K forever, trading steady-state step cost
        for the no-retrace guarantee. After a known-transient burst, ops
        can reclaim the cost with ``reset_slot_floor()`` (one retrace) —
        see OPERATIONS.md "retune"."""
        for family, rt in tensors.items():
            self._slot_floor[family] = max(self._slot_floor[family], rt.slots)

    def reset_slot_floor(self) -> Dict[str, int]:
        """Drop every family's slot floor back to its initial value and
        force a recompile, shrinking the per-slot device loops to what
        the CURRENT rules actually need.

        Costs one fused-step retrace on the next dispatch (the exact
        thing the ratchet exists to avoid) — call it deliberately after
        a transient rule burst, not on a schedule. Returns the floor that
        was in effect before the reset (ops visibility)."""
        with self._config_lock:
            old = dict(self._slot_floor)
            self._slot_floor = dict(INITIAL_SLOT_FLOOR)
            for family in INITIAL_SLOT_FLOOR:
                self._dirty[family] = True
            self._rebuild_leases()
        return old

    def _maybe_start_system_listener(self):
        def is_set(v):
            return v is not None and v >= 0

        if any(
            is_set(r.highest_system_load) or is_set(r.highest_cpu_usage)
            for r in self.system_rules.get_rules()
        ):
            self.system_status.start()

    def warmup(self, widths: Optional[Sequence[int]] = None) -> None:
        """Precompile the fused entry/exit steps for every micro-batch
        ladder width under the CURRENT rule shapes.

        XLA specializes per (batch width, rule-tensor shape); the first
        dispatch of each pair pays a compile (seconds on CPU, 20-40s on
        TPU) while holding the engine lock — so first DEVICE-PATH traffic
        stalls behind the compiler. (Rule pushes do not: they run on the
        config lock and only wait when seeding a newly-eligible resource
        from the device window.) Production boot sequence: load initial
        rules, then ``warmup()``, then serve. No-op batches (all rows -1)
        commit nothing."""
        for width in (widths if widths is not None else BATCH_WIDTHS):
            ebuf = make_entry_batch_np(int(width))  # all rows -1: no-op
            self._run_entry_batch(
                EntryBatch(**{k: jnp.asarray(v) for k, v in ebuf.items()}))
            xbuf = make_exit_batch_np(int(width))
            self._run_exit_batch(
                ExitBatch(**{k: jnp.asarray(v) for k, v in xbuf.items()}))

    def set_occupy_timeout(self, timeout_ms: int) -> None:
        """Retune the prioritized-borrow wait cap at runtime (reference:
        ``OccupyTimeoutProperty``). Capped at one instant window — a
        borrow can never wait past the window it borrows from. A TRACED
        step argument, so tuning is free (no recompile)."""
        timeout_ms = int(timeout_ms)
        with self._lock:
            if timeout_ms < 0 or timeout_ms > self._spec1.interval_ms:
                raise ValueError(
                    f"occupy timeout {timeout_ms}ms must be within "
                    f"[0, {self._spec1.interval_ms}] (one instant window)")
            self._occupy_timeout_ms = timeout_ms

    def set_window_geometry(self, interval_ms: Optional[int] = None,
                            sample_count: Optional[int] = None) -> None:
        """Retune the instant window at runtime (reference:
        ``IntervalProperty`` / ``SampleCountProperty`` — core:node/).

        The 1s-window statistics RESET under the new geometry (upstream
        rebuilds the LeapArray the same way); breakers, param buckets, the
        minute window, and the concurrency gauge survive. Pending occupy
        borrows are dropped — their bucket geometry no longer exists.
        Device shapes are static under jit, so this recompiles the step on
        next use (~one compile, same as a capacity change would).
        """
        from sentinel_tpu.ops import window as W_

        # Pre-retune queued commits belong to the OLD window: land them in
        # it before it is discarded, so neither the reset device window nor
        # the fresh lease mirrors inherit pre-retune usage. (Must happen
        # outside self._lock — the flush dispatch takes it.)
        self._flush_committer()
        with self._config_lock, self._lock:
            cur = self._spec1
            interval_ms = cur.interval_ms if interval_ms is None else int(interval_ms)
            sample_count = cur.buckets if sample_count is None else int(sample_count)
            if interval_ms <= 0 or sample_count <= 0 \
                    or interval_ms % sample_count != 0:
                raise ValueError(
                    f"invalid window geometry: interval {interval_ms}ms must "
                    f"be a positive multiple of sample count {sample_count}")
            new = W_.WindowSpec(interval_ms, sample_count)
            if new == cur:
                return
            self._spec1 = new
            # The borrow-wait cap must stay within one instant window; a
            # shrink below the active cap clamps it (loudly), or borrows
            # would credit buckets that expire before their wait elapses.
            if self._occupy_timeout_ms > new.interval_ms:
                from sentinel_tpu.log.record_log import record_log

                record_log.warn(
                    "occupy timeout %sms clamped to new %sms window",
                    self._occupy_timeout_ms, new.interval_ms)
                self._occupy_timeout_ms = new.interval_ms
            self._rebuild_w1_jits()
            self._rebuild_entry_jit()  # closes over the new spec
            # Reset the device window BEFORE rebuilding leases: the fresh
            # mirrors (new bucket count) must seed from the new-geometry
            # window, not the stale one — seeding old-geometry buckets into
            # new-geometry mirrors corrupts the ring (wrong length) and
            # re-grants/withholds quota the reset already discarded.
            if self._state is not None:
                self._state = self._state._replace(
                    w1=W_.make_window(self.capacity, new),
                    occupied_next=jnp.zeros((self.capacity,), jnp.int32),
                    occupied_stamp=jnp.int64(-1),
                )
            # The shadow world's instant window carries the OLD bucket
            # geometry — rebuild it under the new spec at the next
            # compile (its stats reset with the live window's, same
            # stance as the 1s-window reset above).
            self._dirty["rollout"] = True
            self._rebuild_leases()  # mirrors carry the window geometry

    def close(self) -> None:
        """Stop background workers (pipeline, host OS sampler, cluster role)."""
        # Fast path off FIRST (one atomic swap) so no new entry takes it,
        # then drain and stop the committer; a leased handle exiting after
        # close falls back to the synchronous device path. The flag and the
        # swap happen under the lock _ensure_committer checks them under, so
        # a racing entry either installs its committer before the swap (we
        # stop that one below) or sees _closed and commits inline; stop()
        # runs OUTSIDE the lock — the background flush takes the engine
        # lock, and joining it while holding that lock would deadlock.
        with self._config_lock, self._lock:
            self._closed = True
            self._fastpath = _FastPathState({}, frozenset(), False)
            committer, self._committer = self._committer, None
        if committer is not None:
            committer.stop()
        self.stop_pipeline()
        self.system_status.stop()
        self.cluster.stop()
        self.traces.stop()
        self.slo.stop()
        fleet = self.fleet
        if fleet is not None:
            self.fleet = None
            fleet.stop()
        self.journal.close()

    @staticmethod
    def _cluster_info(rules, with_param_idx: bool = False) -> Dict[str, list]:
        """resource -> [(flowId, fallback[, paramIdx])] for remote-enforced
        (cluster mode + flowId) rules. Pod-psum cluster rules (no flowId)
        stay out: they are enforced by the local/pod check."""
        info: Dict[str, list] = {}
        for r in rules:
            cc = getattr(r, "cluster_config", None) or {}
            if getattr(r, "cluster_mode", False) and cc.get("flowId") is not None:
                entry = (int(cc["flowId"]),
                         bool(cc.get("fallbackToLocalWhenFail", True)))
                if with_param_idx:
                    entry += (int(r.param_idx),)
                info.setdefault(r.resource, []).append(entry)
        return info

    @staticmethod
    def _cluster_threshold_map(rules) -> Dict[int, tuple]:
        """flowId -> (threshold, windowIntervalMs) from the local copies
        of cluster-mode flow rules (the degraded-quota share base) —
        the SAME derivation standalone HA seats use, so every client
        computes the same share (the SEMANTICS.md bound needs that)."""
        from sentinel_tpu.cluster.rules import cluster_thresholds

        return cluster_thresholds(
            r for r in rules if getattr(r, "cluster_mode", False))

    def cluster_degraded_thresholds(self) -> Dict[int, tuple]:
        """Current flowId -> (threshold, intervalMs) map for the HA
        client's DegradedQuota (lock-free: replaced wholesale on load)."""
        return self._cluster_thresholds

    # -- LLM streaming reservations (sentinel_tpu/llm/ — ISSUE 17) ---------

    def _llm_debit(self, resource: str, tokens: int) -> int:
        """Debit ``tokens`` into the model's TPS window through the
        normal entry path, chunked to MAX_ACQUIRE_COUNT (the device
        kernels' exact-count ceiling). QPS PASS debits are
        window-permanent; the immediate exit releases only the
        concurrency channel. On a mid-chunk block the exception carries
        ``llm_debited`` — the tokens already landed — so the caller can
        refund them as expiring credit."""
        remaining = int(tokens)
        debited = 0
        try:
            while remaining > 0:
                chunk = min(remaining, C.MAX_ACQUIRE_COUNT)
                try:
                    handle = self.entry(resource, count=chunk)
                except BlockException as ex:
                    ex.llm_debited = debited
                    raise
                handle.exit()
                debited += chunk
                remaining -= chunk
        finally:
            # Land the leased commits NOW, in this sim second: an
            # injected-clock run (simulator replay) has no on_advance
            # flush hook, so a background flush after clock.advance
            # would stamp these debits into the WRONG window —
            # nondeterministically.
            self._flush_committer()
        return debited

    def stream_open(self, stream_id: str, model: str,
                    estimate_tokens: Optional[int] = None,
                    tenant: str = C.LIMIT_APP_DEFAULT):
        """Open a streaming reservation: acquire the ESTIMATED output
        budget up front as a lease that ticks down as tokens stream
        (``stream_tick``) and reconciles on ``stream_close``. Raises a
        ``BlockException`` subclass when the window (or the
        maxConcurrentStreams cap / ledger capacity) rejects the open;
        any partially-debited estimate is refunded as expiring credit,
        so a rejected open never leaks budget."""
        from sentinel_tpu.core.exceptions import FlowException
        from sentinel_tpu.llm.rules import llm_resource

        resource = llm_resource(model)
        now = self.now_ms()
        estimate = int(self._llm_default_estimate
                       if estimate_tokens is None else estimate_tokens)
        if estimate < 0:
            raise ValueError("estimate_tokens must be >= 0")
        cap = self._llm_max_streams.get(resource)
        if (cap is not None and self.streams.active(resource) >= cap) \
                or self.streams.at_capacity():
            self.streams.open_blocked += 1
            from sentinel_tpu.log.record_log import log_block

            log_block(resource, "FlowException", tenant, estimate, now)
            raise FlowException(resource)
        # The up-front reservation caps at ONE window's token budget: a
        # multi-second generation reserves its first window's worth and
        # pays the rest live as it streams across later windows (the
        # tick's overflow path) — which is also what keeps the abort
        # over-admission bound ≤ one window of tokens (SEMANTICS.md).
        budget = self._llm_window_budget.get(resource)
        reserved = estimate if budget is None \
            else min(estimate, int(budget))
        credit = self.streams.take_credit(resource, reserved, now)
        try:
            debited = self._llm_debit(resource, reserved - int(credit))
        except BlockException as ex:
            # Refund what landed (live chunks + consumed credit): the
            # tokens stay in the PASS window until it rolls, but the
            # credit makes them reusable for that long — no budget leak.
            refund = getattr(ex, "llm_debited", 0) + credit
            self.streams.add_credit(resource, refund, now)
            self.streams.open_blocked += 1
            raise
        return self.streams.open(stream_id, resource, tenant,
                                 estimate, reserved, debited, now)

    def stream_tick(self, stream_id: str, tokens: int) -> float:
        """Reconcile ``tokens`` actually streamed against the
        reservation. Output beyond the estimate debits LIVE (credit
        first), so a runaway generation pays for every token; a block
        on that overflow debit propagates as backpressure (the tokens
        already streamed stay counted). Returns the remaining reserved
        budget."""
        now = self.now_ms()
        covered, overflow = self.streams.tick(stream_id, tokens, now)
        if overflow > 0:
            lease = self.streams.get(stream_id)
            credit = self.streams.take_credit(
                lease.resource, overflow, now)
            try:
                debited = self._llm_debit(
                    lease.resource, int(overflow - int(credit)))
            except BlockException as ex:
                self.streams.record_overflow_debit(
                    getattr(ex, "llm_debited", 0))
                raise
            self.streams.record_overflow_debit(debited)
        lease = self.streams.get(stream_id)
        return lease.remaining if lease is not None else 0.0

    def stream_close(self, stream_id: str, aborted: bool = False) -> float:
        """Close (or abort) a streaming reservation. The unconsumed
        remainder returns as per-resource credit expiring at the window
        roll-off — the over-admission across an abort is bounded by the
        unreconciled estimate for at most one window interval
        (SEMANTICS.md "Streaming-reservation bound"). Returns the
        released remainder."""
        now = self.now_ms()
        lease = self.streams.get(stream_id)
        if lease is None:
            raise KeyError(f"unknown stream {stream_id!r}")
        remainder = self.streams.close(stream_id, now, aborted=aborted)
        if remainder > 0:
            self.streams.add_credit(lease.resource, remainder, now)
        return remainder

    def _refresh_signals(self, now_ms: int) -> None:
        """Fold the latest host OS sample into device state (≤ 1 Hz).

        A clock that stepped BACKWARD (NTP slew, a test re-freezing to an
        earlier epoch, a simulator timebase) must refresh rather than
        wait for real time to catch the stale stamp up — the throttle
        gates only genuinely-recent refreshes."""
        if 0 <= now_ms - self._signals_refreshed_ms < 1000:
            return
        self._signals_refreshed_ms = now_ms
        self._state = self._state._replace(
            sys_signals=jnp.asarray(self.system_status.snapshot()))

    # -- public API --------------------------------------------------------

    def entry(
        self,
        resource: str,
        entry_type: int = C.EntryType.OUT,
        count: int = 1,
        args: Sequence = (),
        prioritized: bool = False,
    ) -> EntryHandle:
        """``SphU.entry``: admit or raise a ``BlockException`` subclass."""
        if count > C.MAX_ACQUIRE_COUNT:
            # The device kernels carry per-request counts through bf16
            # matmul operands, exact only up to 256 (ops/segment.py). The
            # reference's acquireCount is 1 in every shipped call site;
            # reject out-of-range counts loudly instead of silently
            # mis-admitting.
            raise ValueError(
                f"count={count} exceeds MAX_ACQUIRE_COUNT={C.MAX_ACQUIRE_COUNT}")
        ctx = ctx_mod.get_context()
        if ctx is None:
            ctx = ctx_mod.enter_auto()  # pooled per-thread default context
        if ctx.is_null:
            return EntryHandle(self, resource, ctx, -1, -1, -1,
                               entry_type == C.EntryType.IN, count, ())

        if not self.enabled:
            return EntryHandle(self, resource, ctx, -1, -1, -1,
                               entry_type == C.EntryType.IN, count, ())

        if self.slots is not None:
            # Slot mode: admission routes through the bounded hot set
            # (core/slots.py) — hot resources take the normal lease /
            # device machinery at their SLOT row, cold-tail resources
            # degrade loudly to the host lease path; nothing raises at
            # capacity.
            return self._slot_entry(resource, ctx, entry_type, count,
                                    args, prioritized)

        reg = self.registry
        if ctx.entrance_row < 0:
            ctx.entrance_row = reg.entrance_row(ctx.name)
        parent = ctx.cur_entry.dn_row if ctx.cur_entry else ctx.entrance_row
        cluster_row, dn_row, origin_row, origin_id = reg.resolve_entry(
            resource, ctx.name, ctx.origin, parent, int(entry_type))
        entry_in = entry_type == C.EntryType.IN

        if cluster_row < 0:
            # Registry full: pass-through, like the reference's chain cap.
            return EntryHandle(self, resource, ctx, -1, -1, -1, entry_in, count, ())

        params = tuple(_hash_param(a) for a in args[:MAX_PARAMS]) \
            if args else ()

        # SPI host slots (core/spi.py): a slot raising a BlockException
        # rejects the entry; the block is committed to statistics first
        # (the reference's StatisticSlot records custom-slot rejections).
        custom_ex = None
        slots = self._spi.host_slots()
        if slots:
            info = self._spi.EntryInfo(resource=resource, origin=ctx.origin,
                             count=count, entry_type=int(entry_type),
                             prioritized=prioritized, args=tuple(args),
                             context_name=ctx.name)
            for slot in slots:
                try:
                    slot.on_entry(info)
                except BlockException as ex:
                    custom_ex = ex
                    break
                except Exception:
                    # A buggy slot must not leak the auto-created context
                    # (it would shadow the thread's next ContextUtil.enter).
                    ctx_mod.auto_exit_context()
                    raise
        if custom_ex is not None:
            self._submit_entry(
                resource, cluster_row, dn_row, origin_row, origin_id,
                reg.context_id(ctx.name), count, prioritized, entry_in,
                params, skip_cluster=True, pre_blocked=True)
            ctx_mod.auto_exit_context()
            from sentinel_tpu.log.record_log import log_block

            log_block(resource, type(custom_ex).__name__, ctx.origin, count,
                      self.now_ms())
            raise custom_ex

        # Token-lease fast path (core/lease.py): eligible resources admit
        # host-side (device-exact DEFAULT math, serially exact under one
        # lock) and stream their stats to the device asynchronously —
        # sync-path latency drops from one device dispatch to microseconds.
        # (prioritized requests keep the device path: a rejected one may
        # still be granted an occupy-next-window borrow there.)
        fp = self._fastpath  # ONE read: never a torn (leases, guarded, unruled)
        lease = fp.leases.get(resource)
        fast_ok = (not slots and self._pipeline is None
                   and not self._spi.device_checkers())
        if lease is not None and not prioritized and fast_ok:
            now = self.now_ms()
            # admit() returns a BlockReason int (0 = pass): plain leases
            # run the DEFAULT window ring; widened leases (warm-up flow
            # rules, single-param resources — ROADMAP 3c) also mirror the
            # warm-up bucket and the per-value param token buckets, and
            # attribute blocks to the right family.
            block_reason = lease.admit(count, now, params)
            self._ensure_committer().add_entry(
                cluster_row, dn_row, origin_row, entry_in, count,
                block_reason == 0, block_reason)
            if block_reason:
                ctx_mod.auto_exit_context()
                ex = exception_for_reason(block_reason, resource)
                from sentinel_tpu.log.record_log import log_block

                log_block(resource, type(ex).__name__, ctx.origin, count, now)
                raise ex
            handle = EntryHandle(self, resource, ctx, cluster_row, dn_row,
                                 origin_row, entry_in, count, params,
                                 leased=True, now_ms=now)
            ctx.entry_stack.append(handle)
            return handle
        if lease is None and fast_ok and fp.unruled \
                and resource not in fp.guarded:
            # NO rules of any family on this resource (and nothing
            # RELATEs to it): always pass, stats stream via the committer
            # — the dominant real-world case never pays a device dispatch.
            self._ensure_committer().add_entry(
                cluster_row, dn_row, origin_row, entry_in, count, True)
            handle = EntryHandle(self, resource, ctx, cluster_row, dn_row,
                                 origin_row, entry_in, count, params,
                                 leased=True)
            ctx.entry_stack.append(handle)
            return handle

        if lease is not None:
            # Device path on a LEASED resource (prioritized request or the
            # pipeline mode): land pending leased commits first so the
            # device check sees them, and mirror the verdict below so the
            # lease never drifts from the device window.
            self._flush_committer()
        # Cross-process span sampling (telemetry/spans.py): only entries
        # with cluster-mode rules can cross the wire, so only those are
        # sampled — the root "entry" span records the final verdict, the
        # cluster check hangs token_request + server-side spans under it.
        trace_ctx = root_span = None
        if self._cluster_flow_info.get(resource) \
                or self._cluster_param_info.get(resource):
            trace_ctx = self.spans.sample()
        if trace_ctx is not None:
            from sentinel_tpu.telemetry.spans import Span

            root_span = Span("sentinel.entry", trace_ctx,
                             attrs={"resource": resource,
                                    "origin": ctx.origin})
        skip_cluster, pre_blocked = self._cluster_token_check(
            resource, count, prioritized, args, trace=trace_ctx)
        reason, wait_us = self._submit_entry(
            resource, cluster_row, dn_row, origin_row, origin_id,
            reg.context_id(ctx.name), count, prioritized, entry_in, params,
            skip_cluster=skip_cluster, pre_blocked=pre_blocked,
        )
        if root_span is not None:
            root_span.attrs.update(
                reason=int(reason),
                blocked=bool(reason > 0 and reason != C.BlockReason.WAIT),
                preBlocked=bool(pre_blocked))
            self.spans.record(root_span.finish())
        if reason > 0 and reason != C.BlockReason.WAIT:
            # Drop an auto-entered context with no live entries so a fresh
            # ContextUtil.enter on this thread isn't shadowed by it.
            ctx_mod.auto_exit_context()
            ex = exception_for_reason(reason, resource)
            from sentinel_tpu.log.record_log import log_block

            log_block(resource, type(ex).__name__, ctx.origin, count,
                      self.now_ms())
            raise ex
        if wait_us > 0:
            time.sleep(wait_us / 1e6)
        if lease is not None:
            # Occupy grants land in the bucket after the wait — recording
            # post-sleep stamps them there. params keep a widened lease's
            # per-value buckets honest for device-path passes.
            lease.add(count, self.now_ms(), params)

        handle = EntryHandle(self, resource, ctx, cluster_row, dn_row,
                             origin_row, entry_in, count, params)
        ctx.entry_stack.append(handle)
        return handle

    # -- slot-table admission (core/slots.py — ROADMAP 1) ------------------

    def _slot_entry(self, resource: str, ctx, entry_type: int, count: int,
                    args: Sequence, prioritized: bool) -> EntryHandle:
        """entry() in slot mode. Hot resources run the standard lease /
        device machinery at their slot row; cold-tail resources degrade
        LOUDLY: leaseable-ruled -> host-exact lease verdict, everything
        else -> counted pass (unenforced if device-only-ruled). Handles
        carry (slot, generation) so exits can never land on a reused
        slot's successor."""
        from sentinel_tpu.core.slots import COLD_GEN
        from sentinel_tpu.log.record_log import log_block

        slots = self.slots
        entry_in = entry_type == C.EntryType.IN
        params = tuple(_hash_param(a) for a in args[:MAX_PARAMS]) \
            if args else ()
        now = self.now_ms()
        # Intern the name host-side: metadata only (entry/resource type
        # for the metas view, the ops-plane name table) — never a device
        # row. Past registry capacity this degrades loudly (overflow
        # counter) and admission continues: the slot table never needs
        # the registry row to exist.
        self.registry.cluster_row(resource, int(entry_type))
        # The telescope feed drives admit/steal, so it must see EVERY
        # entry at resource grain — cold ones never reach a device batch.
        population = getattr(self, "population", None)
        if population is not None and population.enabled:
            population.observe_pairs(((resource, count),))
        cur = slots.current(resource)
        if cur is None:
            cur = slots.try_admit(resource, now)
        fp = self._fastpath
        lease = fp.leases.get(resource)

        if cur is None:
            # ---- cold tail: no slot, no raise -------------------------
            if lease is not None:
                # Host-exact verdict through the existing lease path —
                # eviction costs stats continuity, never rule fidelity.
                block_reason = lease.admit(count, now, params)
                if block_reason:
                    slots.cold_block(resource, count)
                    slots.note_verdict(resource, -1, COLD_GEN, now // 1000,
                                       "block", block_reason)
                    ctx_mod.auto_exit_context()
                    ex = exception_for_reason(block_reason, resource)
                    log_block(resource, type(ex).__name__, ctx.origin,
                              count, now)
                    raise ex
                slots.cold_pass(resource, count)
            else:
                # Device-only-ruled (guarded) cold resources pass
                # UNENFORCED behind a counter — loud, bounded, and fixed
                # by the pin machinery in steady state; plain unruled
                # cold resources just pass counted.
                unenforced = resource in fp.guarded or not fp.unruled
                slots.cold_pass(resource, count, unenforced=unenforced)
            slots.note_verdict(resource, -1, COLD_GEN, now // 1000,
                               "pass", 0)
            handle = EntryHandle(self, resource, ctx, -1, -1, -1, entry_in,
                                 count, params, now_ms=now)
            handle.slot_gen = COLD_GEN
            ctx.entry_stack.append(handle)
            return handle

        slots.hot_hits_total += 1
        slot, gen = cur
        fast_ok = (not self._spi.host_slots()
                   and not self._spi.device_checkers())
        if lease is not None and not prioritized and fast_ok:
            # ---- leased-hot: host verdict, committer commit -----------
            block_reason = lease.admit(count, now, params)
            # Committer BEFORE gate: its lazy construction takes _lock,
            # and the lock order is _lock -> gate, never the reverse.
            committer = self._ensure_committer()
            with slots.gate:
                cur2 = slots._hot.get(resource)
                if cur2 is not None:
                    # Re-translated under the gate: the enqueue can never
                    # target a slot whose tenancy already changed.
                    committer.add_entry(cur2[0], -1, -1, entry_in, count,
                                        block_reason == 0, block_reason)
                    slot, gen = cur2
            if cur2 is None:
                # Evicted between translation and enqueue: the verdict
                # stands (host-exact), the stats tally cold.
                if block_reason:
                    slots.cold_block(resource, count)
                else:
                    slots.cold_pass(resource, count)
            if block_reason:
                slots.note_verdict(resource, slot if cur2 else -1,
                                   gen if cur2 else COLD_GEN, now // 1000,
                                   "block", block_reason)
                ctx_mod.auto_exit_context()
                ex = exception_for_reason(block_reason, resource)
                log_block(resource, type(ex).__name__, ctx.origin, count,
                          now)
                raise ex
            slots.note_verdict(resource, slot if cur2 else -1,
                               gen if cur2 else COLD_GEN, now // 1000,
                               "pass", 0)
            handle = EntryHandle(self, resource, ctx, cur2[0] if cur2
                                 else -1, -1, -1, entry_in, count, params,
                                 leased=cur2 is not None, now_ms=now)
            handle.slot_gen = gen if cur2 else COLD_GEN
            ctx.entry_stack.append(handle)
            return handle

        # ---- device path at the slot row ------------------------------
        # SPI host slots keep their veto (the reference's custom-slot
        # chain): a BlockException pre-blocks the device commit.
        pre_blocked = False
        custom_ex = None
        spi_slots = self._spi.host_slots()
        if spi_slots:
            info = self._spi.EntryInfo(
                resource=resource, origin=ctx.origin, count=count,
                entry_type=int(entry_type), prioritized=prioritized,
                args=tuple(args), context_name=ctx.name)
            for spi_slot in spi_slots:
                try:
                    spi_slot.on_entry(info)
                except BlockException as ex:
                    custom_ex, pre_blocked = ex, True
                    break
                except Exception:
                    ctx_mod.auto_exit_context()
                    raise
        if lease is not None:
            # Pending leased commits must land before the device check.
            self._flush_committer()
        skip_cluster, cluster_blocked = self._cluster_token_check(
            resource, count, prioritized, args)
        oid = self.registry.origin_id(ctx.origin)
        fields = dict(
            cluster_row=-1, dn_row=-1, origin_row=-1, origin_id=oid,
            origin_named=oid in self._named_origins.get(resource, ()),
            context_id=self.registry.context_id(ctx.name), count=count,
            prioritized=prioritized, entry_in=entry_in,
            skip_cluster=skip_cluster,
            pre_blocked=pre_blocked or cluster_blocked, params=params)
        reason, wait_us, cur2 = self._slot_submit(resource, fields)
        if custom_ex is not None:
            ctx_mod.auto_exit_context()
            log_block(resource, type(custom_ex).__name__, ctx.origin,
                      count, now)
            raise custom_ex
        if cur2 is None:
            # Tenancy changed between translation and dispatch: nothing
            # committed — serve the entry as a counted cold pass.
            slots.cold_pass(resource, count)
            slots.note_verdict(resource, -1, COLD_GEN, now // 1000,
                               "pass", 0)
            handle = EntryHandle(self, resource, ctx, -1, -1, -1, entry_in,
                                 count, params, now_ms=now)
            handle.slot_gen = COLD_GEN
            ctx.entry_stack.append(handle)
            return handle
        slot, gen = cur2
        if reason > 0 and reason != C.BlockReason.WAIT:
            slots.note_verdict(resource, slot, gen, now // 1000, "block",
                               int(reason))
            ctx_mod.auto_exit_context()
            ex = exception_for_reason(reason, resource)
            log_block(resource, type(ex).__name__, ctx.origin, count,
                      self.now_ms())
            raise ex
        if wait_us > 0:
            time.sleep(wait_us / 1e6)
        if lease is not None:
            lease.add(count, self.now_ms(), params)
        slots.note_verdict(resource, slot, gen, now // 1000, "pass", 0)
        handle = EntryHandle(self, resource, ctx, slot, -1, -1, entry_in,
                             count, params, now_ms=now)
        handle.slot_gen = gen
        ctx.entry_stack.append(handle)
        return handle

    def _slot_submit(self, resource: str,
                     fields: Dict) -> Tuple[int, int, Optional[Tuple[int, int]]]:
        """Width-1 device dispatch with in-lock tenancy re-validation:
        the slot row is resolved INSIDE ``_lock`` (steal surgery holds
        it), so a commit can only land under live tenancy. Returns
        (reason, wait_us, (slot, gen) committed under) — (0, 0, None)
        when the resource went cold first (nothing committed)."""
        slots = self.slots
        with self._lock:
            cur = slots.current(resource)
            if cur is None:
                return 0, 0, None
            fields = dict(fields, cluster_row=cur[0])
            buf = make_entry_batch_np(1)
            for k, v in fields.items():
                if k == "params":
                    for i, h in enumerate(v):
                        buf["param_hash"][0, i] = h
                        buf["param_present"][0, i] = True
                else:
                    buf[k][0] = v
            try:
                dec = self._run_entry_batch_locked(EntryBatch(**buf))
            except DeviceDispatchError as ex:
                self._note_fail_open(str(ex))
                return 0, 0, cur
            return int(dec.reason[0]), int(dec.wait_us[0]), cur

    def _slot_exit(self, handle: EntryHandle, count: int) -> None:
        """_do_exit in slot mode. A resource hot NOW (any generation)
        exits at its CURRENT slot — the grafted cur_threads gauge nets
        to zero there; evicted-and-still-cold exits decrement the spill
        record and tally host-side; cold-path entries always tally
        host-side."""
        from sentinel_tpu.core.slots import COLD_GEN

        slots = self.slots
        now = self.now_ms()
        rt = min(max(0, now - handle.created_ms), C.DEFAULT_MAX_RT_MS)
        if handle.slot_gen == COLD_GEN:
            slots.cold_exit(handle.resource, count, rt, handle.error)
            ctx_mod.auto_exit_context()
            return
        committer = self._committer  # one read: close() nulls it
        if handle.leased and committer is not None:
            with slots.gate:
                cur = slots._hot.get(handle.resource)
                if cur is not None:
                    committer.add_exit(cur[0], -1, -1, handle.entry_in,
                                       count, rt, True, handle.error)
            if cur is None:
                slots.evicted_exit(handle.resource, count, rt,
                                   handle.error, now)
            ctx_mod.auto_exit_context()
            return
        with self._lock:
            cur = slots.current(handle.resource)
            if cur is not None:
                buf = make_exit_batch_np(1)
                buf["cluster_row"][0] = cur[0]
                buf["dn_row"][0] = -1
                buf["origin_row"][0] = -1
                buf["entry_in"][0] = handle.entry_in
                buf["count"][0] = count
                buf["rt_ms"][0] = rt
                buf["success"][0] = True
                buf["error"][0] = handle.error
                for i, h in enumerate(handle.params):
                    buf["param_hash"][0, i] = h
                    buf["param_present"][0, i] = True
                try:
                    self._run_exit_batch(ExitBatch(**buf))
                except DeviceDispatchError as ex:
                    self._note_fail_open(str(ex))
        if cur is None:
            slots.evicted_exit(handle.resource, count, rt, handle.error,
                               now)
        ctx_mod.auto_exit_context()

    def _device_metas(self):
        """Row-indexed meta view of the DEVICE tensor: the registry in
        fixed-capacity mode, the slot table's tenancy view in slot mode.
        Every consumer that renders device rows to names reads through
        here, so a reused slot renders as its CURRENT occupant only."""
        slots = getattr(self, "slots", None)
        return self.registry.meta if slots is None else slots.device_metas()

    def _device_resources(self) -> Dict[str, int]:
        """resource -> device row of everything with a live device row."""
        slots = getattr(self, "slots", None)
        return self.registry.resources() if slots is None \
            else slots.resources()

    def _device_row_of(self, resource: str) -> Optional[int]:
        """Current device row for one resource, or None (cold / never
        registered). Delegates to the slot table's single translation
        implementation in slot mode."""
        slots = getattr(self, "slots", None)
        if slots is None:
            return self.registry.get_cluster_row(resource)
        return slots.device_row(resource)

    def _rule_registry(self):
        """What the rule compilers resolve rows through: the registry in
        fixed-capacity mode, the slot table's facade in slot mode (rows
        are slots; a cold ruled resource compiles inert — the pin
        machinery prevents that outside pin overflow)."""
        slots = getattr(self, "slots", None)
        return self.registry if slots is None else slots.rule_registry_view()

    def _slot_pinned_resources(self) -> set:
        """Resources compiled rules target (live + rollout candidate):
        PINNED hot — the rule tensors hold their slot indices, so
        evicting one would apply its rule to the slot's successor."""
        slots = getattr(self, "slots", None)
        if slots is None:
            return set()
        pinned: set = set()

        def _add(rules) -> None:
            for r in rules:
                res = getattr(r, "resource", "")
                if res:
                    pinned.add(res)
                ref = getattr(r, "ref_resource", "")
                if ref:
                    pinned.add(ref)

        _add(self.flow_rules.get_rules())
        _add(self.degrade_rules.get_rules())
        _add(self.param_rules.get_rules())
        _add(self.authority_rules.get_rules())
        rollout = getattr(self, "rollout", None)
        spec = rollout.device_spec() if rollout is not None else None
        if spec:
            for fam in ("flow", "degrade", "authority", "param"):
                _add(spec.get(fam) or ())
        return pinned

    def _slots_sync_pins(self) -> None:
        """Config-plane hook on every rule push: admit (stealing if
        needed) every newly ruled resource BEFORE its rules compile.
        Runs OUTSIDE the config lock's critical section is fine too —
        lock order stays config -> engine -> gate throughout. If pinning
        changed occupancy, every family re-dirties: the pin admits were
        published AFTER any compile the admission surgery itself ran, so
        the next dispatch must recompile against the final mapping."""
        slots = self.slots
        if slots is None:
            return
        before = slots.admits_total
        slots.ensure_pinned(self._slot_pinned_resources(), self.now_ms())
        if slots.admits_total != before:
            with self._config_lock:
                for fam in ("flow", "degrade", "authority", "param"):
                    self._dirty[fam] = True

    def _note_fail_open(self, why: str) -> None:
        """Count + rate-limited log of an unguarded pass-through."""
        self.fail_open_count += 1
        now = self.now_ms()
        if now - self._fail_open_logged_ms >= 1000:
            self._fail_open_logged_ms = now
            import logging

            logging.getLogger("sentinel_tpu").warning(
                "entry passed UNGUARDED (%s); fail_open_count=%d",
                why, self.fail_open_count)

    def _note_cluster_fallback(self, budget_exhausted: bool = False) -> None:
        """A cluster-mode rule degraded to its local fallback this entry."""
        self.cluster_fallback_count += 1
        if budget_exhausted:
            self.cluster_budget_exhausted_count += 1

    def _cluster_token_check(self, resource, count, prioritized, args,
                             trace=None) -> Tuple[bool, bool]:
        """Remote token acquire for cluster-mode rules (``passClusterCheck``).

        Returns (skip_cluster, pre_blocked): with a healthy token client,
        OK/SHOULD_WAIT verdicts mask the cluster rules out of the local
        check; BLOCKED pre-decides the entry; FAIL-class statuses keep the
        local check live when the rule's fallbackToLocalWhenFail is set
        (= ``fallbackToLocalOrPass``). No client/no cluster rules -> local
        (or pod-psum) enforcement as-is.

        Bounded latency: ALL remote work for one entry — request waits
        AND server-hinted SHOULD_WAIT sleeps, across every cluster rule —
        shares one ``cluster_entry_budget_ms`` deadline budget. A slow,
        hung, or partitioned token server costs the data path at most the
        budget, never a socket timeout per rule; rules the budget can't
        reach degrade to the local check. The client's own breaker
        (resilience.HealthGate) makes the steady degraded state
        effectively free: once OPEN, request_token fails fast without
        touching the wire.
        """
        # Lock-free fast path: the info dicts are replaced wholesale on rule
        # load, and the common no-cluster-rules deployment returns here
        # without touching the engine lock.
        flow_info = self._cluster_flow_info.get(resource, ())
        param_info = self._cluster_param_info.get(resource, ())
        if not flow_info and not param_info:
            return False, False
        client = self.cluster.client_if_active()
        if client is None:
            return False, False
        from sentinel_tpu.cluster.constants import TokenResultStatus

        def traced_call(kind, flow_id, fn):
            """Run one remote acquire under a child span when tracing;
            the server-side span (shipped in the response TLV) joins the
            local collector so the stitched trace reads in one place."""
            if trace is None:
                return fn(None)
            from sentinel_tpu.telemetry.spans import Span, TraceContext

            child = trace.child()
            sp = Span("cluster.token_request", child,
                      parent_span_id=trace.span_id,
                      attrs={"flowId": flow_id, "kind": kind})
            tr = fn(child)
            sp.finish()
            sp.attrs["status"] = int(tr.status)
            self.spans.record(sp)
            if tr.server_span is not None:
                srv = tr.server_span
                self.spans.record_remote(
                    TraceContext(trace.trace_id, srv["spanId"]),
                    "cluster.token_service", child.span_id,
                    srv["startMs"], srv["durationUs"],
                    attrs={"flowId": flow_id})
            return tr

        budget = DeadlineBudget(self.cluster_entry_budget_ms)
        # A request launched with less than half the configured budget
        # left is breaker-NEUTRAL on timeout: a healthy server can miss a
        # starved deadline (earlier rules / SHOULD_WAIT sleeps ate it),
        # and such misses must not trip the gate.
        neutral_below_ms = self.cluster_entry_budget_ms / 2
        all_ok = True
        for flow_id, fallback in flow_info:
            remaining_ms = budget.remaining_ms()
            if remaining_ms <= 0:
                if fallback:
                    all_ok = False
                self._note_cluster_fallback(budget_exhausted=True)
                continue
            tr = traced_call("flow", flow_id, lambda t: client.request_token(
                flow_id, count, prioritized, timeout_s=remaining_ms / 1000.0,
                gate_neutral=remaining_ms < neutral_below_ms, trace=t))
            if tr.status == TokenResultStatus.OK:
                continue
            if tr.status == TokenResultStatus.SHOULD_WAIT:
                wait_ms = budget.clamp_wait_ms(tr.wait_ms)
                if wait_ms > 0:
                    time.sleep(wait_ms / 1000.0)
                continue
            if tr.status == TokenResultStatus.BLOCKED:
                return False, True
            if tr.status == TokenResultStatus.OVERLOADED:
                # Server shed this acquire before admission: degrade to
                # the local lease/fallback path IMMEDIATELY — no retry,
                # no sleep (the retry-after hint governs the failover
                # client's target backoff, not the data path: callers
                # get bounded latency, never a queued wait).
                self.cluster_overload_count += 1
                if fallback:
                    all_ok = False
                    self._note_cluster_fallback()
                continue
            if tr.status == TokenResultStatus.WRONG_SLICE:
                # The leader we reached no longer owns this flow's hash
                # slice and the client could not self-heal within this
                # entry (cluster/sharding.py): not a verdict — degrade
                # to the local check like a FAIL, separately counted so
                # a stale-map storm is visible in resilience_stats.
                self.cluster_wrong_slice_count += 1
                if fallback:
                    all_ok = False
                    self._note_cluster_fallback()
                continue
            if fallback:  # FAIL / NO_RULE / TOO_MANY_REQUEST -> local check
                all_ok = False
                self._note_cluster_fallback()
        for flow_id, fallback, param_idx in param_info:
            if param_idx >= len(args):
                continue  # no such argument: the rule does not apply
            remaining_ms = budget.remaining_ms()
            if remaining_ms <= 0:
                if fallback:
                    all_ok = False
                self._note_cluster_fallback(budget_exhausted=True)
                continue
            tr = traced_call(
                "param", flow_id, lambda t: client.request_param_token(
                    flow_id, count, [args[param_idx]],
                    timeout_s=remaining_ms / 1000.0,
                    gate_neutral=remaining_ms < neutral_below_ms, trace=t))
            if tr.status == TokenResultStatus.OK:
                continue
            if tr.status == TokenResultStatus.BLOCKED:
                return False, True
            if tr.status == TokenResultStatus.OVERLOADED:
                self.cluster_overload_count += 1
                if fallback:
                    all_ok = False
                    self._note_cluster_fallback()
                continue
            if tr.status == TokenResultStatus.WRONG_SLICE:
                self.cluster_wrong_slice_count += 1
                if fallback:
                    all_ok = False
                    self._note_cluster_fallback()
                continue
            if fallback:
                all_ok = False
                self._note_cluster_fallback()
        return all_ok, False

    def _submit_entry(self, resource, cluster_row, dn_row, origin_row,
                      origin_id, context_id, count, prioritized, entry_in,
                      params, skip_cluster=False, pre_blocked=False) -> Tuple[int, int]:
        fields = dict(
            cluster_row=cluster_row, dn_row=dn_row, origin_row=origin_row,
            origin_id=origin_id,
            origin_named=origin_id in self._named_origins.get(resource, ()),
            context_id=context_id, count=count, prioritized=prioritized,
            entry_in=entry_in, skip_cluster=skip_cluster,
            pre_blocked=pre_blocked, params=params,
        )
        pipeline = self._pipeline
        if pipeline is not None:
            ticket = pipeline.submit_entry(fields)
            # A submitted ticket is completed exactly once — by a cycle or
            # by stop()'s straggler drain — so NEVER resubmit it (that
            # would double-commit the stats). Only a None ticket (closed
            # before submit) takes the synchronous path.
            if ticket is not None:
                while not ticket.done.wait(timeout=2.0):
                    if pipeline.closed and not ticket.done.wait(timeout=2.0):
                        # Stop() drained everything it could and the ticket
                        # never surfaced (collector died mid-cycle): pass
                        # unguarded rather than risk a double commit.
                        self._note_fail_open("collector died mid-cycle")
                        return 0, 0
                if ticket.reason == -2:  # cycle error: pass-through
                    self._note_fail_open("pipeline cycle error")
                    return 0, 0
                return ticket.reason, ticket.wait_us
        with self._lock:
            buf = make_entry_batch_np(1)
            for k, v in fields.items():
                if k == "params":
                    for i, h in enumerate(v):
                        buf["param_hash"][0, i] = h
                        buf["param_present"][0, i] = True
                else:
                    buf[k][0] = v
            try:
                dec = self._run_entry_batch_locked(EntryBatch(**buf))
            except DeviceDispatchError as ex:  # backend/tunnel death only
                self._note_fail_open(str(ex))
                return 0, 0  # fail open, like fallbackToLocalOrPass
            return int(dec.reason[0]), int(dec.wait_us[0])

    def _run_entry_batch_locked(self, batch: EntryBatch) -> Decisions:
        self._ensure_compiled()
        now = self.now_ms()
        self._refresh_signals(now)
        try:
            self._state, dec = timed_call(
                self.step_timer, "entry", batch.size, self._entry_jit,
                self._state, self._rules, batch, now,
                occupy_timeout_ms=self._occupy_timeout_ms,
                shadow_rules=self._shadow_rules,
                canary_bps=self._canary_bps,
                canary_salt=self._canary_salt)
        except Exception as ex:  # noqa: BLE001 — dispatch only (donation)
            self._state = None  # buffers possibly consumed: restart cold
            raise DeviceDispatchError(f"entry dispatch failed: {ex!r:.200}") from ex
        # Sampled decision traces: enqueue only (the worker materializes
        # off this thread) — never blocks the step stream.
        self.traces.submit(batch, dec, now)
        self._observe_population(batch)
        return dec

    def _run_entry_batch(self, batch: EntryBatch) -> Decisions:
        with self._lock:
            return self._run_entry_batch_locked(batch)

    def _run_exit_batch(self, batch: ExitBatch) -> None:
        with self._lock:
            self._ensure_compiled()
            now = self.now_ms()
            try:
                self._state = timed_call(
                    self.step_timer, "exit", batch.size, self._exit_jit,
                    self._state, self._rules, batch, now,
                    shadow_rules=self._shadow_rules)
            except Exception as ex:  # noqa: BLE001
                self._state = None
                raise DeviceDispatchError(
                    f"exit dispatch failed: {ex!r:.200}") from ex

    def harvest_decisions(self, dec: Decisions) -> Tuple[np.ndarray,
                                                         np.ndarray]:
        """Materialize a previously dispatched cycle's verdicts (the
        pipeline's harvest phase). Runs WITHOUT the engine lock — the
        arrays belong to an already-enqueued step, so blocking here never
        stalls a concurrent dispatch. An async compute failure surfaces
        HERE (not at dispatch) under JAX's deferred execution: drop the
        state cold exactly like a dispatch-time failure — the catcher
        fails its tickets open and the next dispatch rebuilds."""
        try:
            return np.asarray(dec.reason), np.asarray(dec.wait_us)
        except Exception as ex:  # noqa: BLE001 — backend/tunnel death
            with self._lock:
                self._state = None
            raise DeviceDispatchError(
                f"harvest failed: {ex!r:.200}") from ex

    # -- pipelined mode ----------------------------------------------------

    def start_pipeline(self, max_batch: int = 2048,
                       linger_s: Optional[float] = None,
                       inflight_depth: Optional[int] = None) -> "object":
        """Switch to micro-batched admission (``core/pipeline.py``):
        concurrent entries fold into one device step per cycle, with up
        to ``inflight_depth`` cycles overlapped on the device stream.
        ``linger_s``/``inflight_depth`` default to the
        ``csp.sentinel.pipeline.*`` config keys."""
        from sentinel_tpu.core.pipeline import Pipeline

        if self.slots is not None:
            raise RuntimeError(
                "pipelined admission is not supported in slot mode: the "
                "pipeline resolves rows outside the slot-tenancy "
                "re-validation protocol (run slot mode synchronous, or "
                "fixed-capacity mode pipelined)")
        with self._lock:
            if self._pipeline is None:
                self._ensure_compiled()  # compile before the loop starts
                self._pipeline = Pipeline(
                    self, max_batch, linger_s,
                    inflight_depth=inflight_depth).start()
            return self._pipeline

    def stop_pipeline(self) -> None:
        with self._pipeline_stats_lock:
            pipeline, self._pipeline = self._pipeline, None
            if pipeline is None:
                return  # a concurrent stop owns (or already folded) it
            self._retiring_pipeline = pipeline
        pipeline.stop()  # may drain for seconds — counters stay readable
        with self._pipeline_stats_lock:
            s = pipeline.stats()
            t = self._pipeline_totals
            for k in ("cycles", "batched", "harvests", "failOpenCycles",
                      "poolAllocated", "poolReused"):
                t[k] += s[k]
            t["inflightDepthMax"] = max(t["inflightDepthMax"],
                                        s["inflightDepthMax"])
            self._retiring_pipeline = None

    def pipeline_stats(self) -> Dict:
        """One ops view of pipelined admission: monotone cycle/entry
        counters across pipeline generations (a stopping pipeline keeps
        reporting through the retiring hand-off — no counter dip), the
        live in-flight depth, and the queue-wait vs device-wait split
        from the StepTimer. Never touches the engine lock."""
        with self._pipeline_stats_lock:
            t = dict(self._pipeline_totals)
            p = self._pipeline or self._retiring_pipeline
            live = p.stats() if p is not None else None
            active = self._pipeline is not None
        out = {
            "active": active,
            "cycles": t["cycles"] + (live["cycles"] if live else 0),
            "batched": t["batched"] + (live["batched"] if live else 0),
            "harvests": t["harvests"] + (live["harvests"] if live else 0),
            "failOpenCycles": t["failOpenCycles"]
            + (live["failOpenCycles"] if live else 0),
            "inflightDepth": live["inflightDepth"] if live else 0,
            "inflightDepthMax": max(
                t["inflightDepthMax"],
                live["inflightDepthMax"] if live else 0),
            "configuredDepth": live["configuredDepth"] if live else 0,
            "poolAllocated": t["poolAllocated"]
            + (live["poolAllocated"] if live else 0),
            "poolReused": t["poolReused"]
            + (live["poolReused"] if live else 0),
        }
        out.update(self.step_timer.pipeline_snapshot())
        return out

    def _do_exit(self, handle: EntryHandle, count: int) -> None:
        ctx = handle.context
        if ctx.entry_stack and ctx.entry_stack[-1] is handle:
            ctx.entry_stack.pop()
        elif handle in ctx.entry_stack:
            ctx.entry_stack.remove(handle)
        if self.slots is not None and handle.slot_gen != -1:
            # Slot mode: generation-stamped exit accounting (current-slot
            # device exit / spill-record decrement / cold tally).
            self._slot_exit(handle, count)
            return
        if handle.cluster_row < 0:
            ctx_mod.auto_exit_context()
            return
        now = self.now_ms()
        rt = max(0, now - handle.created_ms)
        slots = self._spi.host_slots()
        if slots:
            info = self._spi.EntryInfo(
                resource=handle.resource, origin=ctx.origin, count=count,
                entry_type=(C.EntryType.IN if handle.entry_in
                            else C.EntryType.OUT),
                prioritized=False, args=(), context_name=ctx.name)
            for slot in slots:
                try:
                    slot.on_exit(info, rt, handle.error)
                except Exception as ex:
                    # Exit hooks never break the real exit, but a broken
                    # slot must be observable, not silent.
                    from sentinel_tpu.log.record_log import record_log

                    record_log.warn("SPI slot %r on_exit failed: %r",
                                    type(slot).__name__, ex)
        committer = self._committer  # one read: close() nulls it concurrently
        if handle.leased and committer is not None:
            # Leased entries complete through the async committer too; the
            # device's RT/success/exception stats converge within one flush.
            # (After close() the committer is gone — fall through to the
            # synchronous device commit below rather than resurrecting it.)
            committer.add_exit(
                handle.cluster_row, handle.dn_row, handle.origin_row,
                handle.entry_in, count, min(rt, C.DEFAULT_MAX_RT_MS),
                True, handle.error)
            ctx_mod.auto_exit_context()
            return
        fields = dict(
            cluster_row=handle.cluster_row, dn_row=handle.dn_row,
            origin_row=handle.origin_row, entry_in=handle.entry_in,
            count=count, rt_ms=min(rt, C.DEFAULT_MAX_RT_MS), success=True,
            error=handle.error, params=handle.params,
        )
        pipeline = self._pipeline
        submitted = pipeline is not None and pipeline.submit_exit(fields)
        if not submitted:
            buf = make_exit_batch_np(1)
            for k, v in fields.items():
                if k == "params":
                    for i, h in enumerate(v):
                        buf["param_hash"][0, i] = h
                        buf["param_present"][0, i] = True
                else:
                    buf[k][0] = v
            try:
                self._run_exit_batch(ExitBatch(**buf))
            except DeviceDispatchError as ex:
                # An exit commit is pure statistics; an infrastructure
                # failure here must never break the caller's happy path.
                self._note_fail_open(str(ex))
        ctx_mod.auto_exit_context()

    # -- batch API (bench / pipelined engine / cluster frontends) ---------

    def check_batch(self, batch: EntryBatch, now_ms: Optional[int] = None) -> Decisions:
        with self._lock:
            self._ensure_compiled()
            now = now_ms if now_ms is not None else self.now_ms()
            self._refresh_signals(now)
            try:
                self._state, dec = self._entry_jit(
                    self._state, self._rules, batch, now,
                    occupy_timeout_ms=self._occupy_timeout_ms,
                    shadow_rules=self._shadow_rules,
                    canary_bps=self._canary_bps,
                    canary_salt=self._canary_salt)
            except Exception as ex:  # noqa: BLE001
                self._state = None
                raise DeviceDispatchError(
                    f"entry dispatch failed: {ex!r:.200}") from ex
            self.traces.submit(batch, dec, now)
            self._observe_population(batch)
            return dec

    def complete_batch(self, batch: ExitBatch, now_ms: Optional[int] = None) -> None:
        with self._lock:
            self._ensure_compiled()
            now = now_ms if now_ms is not None else self.now_ms()
            try:
                self._state = self._exit_jit(self._state, self._rules, batch,
                                             now,
                                             shadow_rules=self._shadow_rules)
            except Exception as ex:  # noqa: BLE001
                self._state = None
                raise DeviceDispatchError(
                    f"exit dispatch failed: {ex!r:.200}") from ex

    # -- metric log source (ops plane) ------------------------------------

    def seal_metrics(self, now_ms: Optional[int] = None) -> List:
        """Aggregate sealed (fully elapsed) seconds from the minute window.

        Reference: ``MetricTimerListener`` walking every ClusterNode's
        minute-window buckets (SURVEY.md §3.5). Here it is one device slice:
        ``w60.counts[:, sealed_bucket_idx, :]`` for all resources at once.
        Returns ``MetricNode``s (timestamps set) for seconds not yet sealed
        by a previous call; all-idle resource-seconds are skipped.
        """
        from sentinel_tpu.core.registry import KIND_CLUSTER
        from sentinel_tpu.metrics.metric_node import MetricNode

        now = now_ms if now_ms is not None else self.now_ms()
        now_sec = now // 1000
        self._flush_committer()  # leased commits land before sealing
        with self._lock:
            self._ensure_compiled()
            first = max(self._sealed_sec + 1, now_sec - C.MINUTE_BUCKETS + 1)
            seconds = list(range(first, now_sec))
            if not seconds:
                return []
            self._sealed_sec = seconds[-1]
            # Fold any completed staged second into w60 before reading it
            # (the step stages the live second in state.sec — see ops/step).
            self._state = self._flush_jit(self._state, now)
            # Pad the bucket-index vector to a power-of-two ladder so a
            # backlog (k up to MINUTE_BUCKETS after a stall) costs at most
            # log2(60) distinct compiles ever — never a fresh XLA compile
            # inside this lock per new backlog length.
            k = len(seconds)
            k_pad = 1 << (k - 1).bit_length()
            idx_list = [s % C.MINUTE_BUCKETS for s in seconds]
            idx = jnp.asarray(idx_list + [idx_list[0]] * (k_pad - k),
                              jnp.int32)
            # One compiled program: rotate + gather + transpose to
            # [R, k, E] on device, ONE host transfer. (Measured at 10k
            # resources / 32k rows, CPU backend: the previous eager path
            # was ~3.3 s per 1 Hz cycle inside this lock; now ~50 ms —
            # dominated by MetricNode construction for active rows.)
            slices = np.asarray(self._w60_read_jit(
                self._state, jnp.asarray(now, jnp.int64), idx))[:, :k]
            threads = np.asarray(self._state.cur_threads)    # [R]
            metas = self._device_metas()
        # Vectorized active scan: only (row, second) pairs with any
        # pass/block/success/exception produce a MetricNode.
        ev = [C.MetricEvent.PASS, C.MetricEvent.BLOCK,
              C.MetricEvent.SUCCESS, C.MetricEvent.EXCEPTION]
        active_rows, active_k = np.nonzero(slices[:, :, ev].any(axis=2))
        out = []
        for row, k in zip(active_rows.tolist(), active_k.tolist()):
            m = metas[row]
            if m.kind != KIND_CLUSTER:
                continue
            t = slices[row, k]
            succ = int(t[C.MetricEvent.SUCCESS])
            out.append(MetricNode(
                timestamp=seconds[k] * 1000,
                resource=m.resource,
                pass_qps=int(t[C.MetricEvent.PASS]),
                block_qps=int(t[C.MetricEvent.BLOCK]),
                success_qps=succ,
                exception_qps=int(t[C.MetricEvent.EXCEPTION]),
                rt=float(t[C.MetricEvent.RT]) / max(succ, 1),
                occupied_pass_qps=int(t[C.MetricEvent.OCCUPIED_PASS]),
                concurrency=int(threads[row]),
                classification=m.resource_type,
            ))
        # Writers expect (second, registration) order; sort by timestamp.
        out.sort(key=lambda n: n.timestamp)
        return out

    # -- introspection (ops plane) ----------------------------------------

    def resilience_stats(self) -> Dict:
        """One ops view of every degradation channel: fail-open passes,
        cluster-rule local fallbacks, the token client's breaker, and the
        registered health probes (datasource pollers, heartbeat) with
        last-success ages. Lock-free — plain counter/snapshot reads."""
        from sentinel_tpu import resilience

        now = self.now_ms()
        out: Dict = {
            "failOpenCount": self.fail_open_count,
            "clusterFallbackCount": self.cluster_fallback_count,
            "clusterBudgetExhaustedCount": self.cluster_budget_exhausted_count,
            "clusterOverloadCount": self.cluster_overload_count,
            "clusterWrongSliceCount": self.cluster_wrong_slice_count,
            "clusterEntryBudgetMs": self.cluster_entry_budget_ms,
            "tokenClientBreaker": None,
            # Frontend overload (ISSUE 6): the embedded token server's
            # admission-queue depth/bounds and shed counters, None while
            # this instance is not a server.
            "overload": self.cluster.overload_stats(),
            # Wire path (ISSUE 11): the reactor frontend's connection /
            # coalescing / RTT snapshot, None while not a reactor server.
            "wire": self.cluster.wire_stats(),
            # Staged-rollout guardrail beside the degradation channels:
            # active candidate set, stage, and windows-to-abort — one
            # unified picture of everything currently between the live
            # ruleset and what traffic actually experiences.
            "rollout": self.rollout.guardrail_state(),
            # Cluster HA (cluster/ha.py): current role, leadership epoch,
            # failovers, degraded-quota spells — failover state without
            # scraping /metrics.
            "clusterHA": self.cluster.ha_stats(),
            # Closed-loop adaptive limiting (sentinel_tpu/adaptive/):
            # enabled/frozen state, in-flight candidate, and the
            # proposal/promotion/abort counters — what the loop is doing
            # to the rules, beside what the rules are doing to traffic.
            "adaptive": self.adaptive.guardrail_state(),
            "probes": {},
        }
        client = self.cluster.token_client
        gate = getattr(client, "health_gate", None)
        if gate is not None:
            out["tokenClientBreaker"] = gate.snapshot()
        for name, snap in resilience.health_snapshot().items():
            for key in ("lastSuccessMs", "lastCheckMs"):
                v = snap.get(key)
                if isinstance(v, (int, float)) and v > 0:
                    snap[key.replace("Ms", "AgeMs")] = max(0, now - int(v))
            out["probes"][name] = snap
        return out

    def shadow_counts(self) -> Optional[np.ndarray]:
        """Cumulative rollout counters since the candidate was installed:
        ``np.int64[S.NUM_SHADOW_COUNTERS, R]`` (would-pass/would-block per
        family beside the live outcome of the same lanes), or None when no
        candidate holds the device. The rollout manager's guardrail and
        the dashboard diff view read through this."""
        with self._lock:
            self._ensure_compiled()
            st = self._state
            if st is None or st.shadow is None:
                return None
            return np.asarray(st.shadow.counts)

    def telemetry_counts(self) -> Dict[str, np.ndarray]:
        """Cumulative device telemetry since engine start, as numpy:
        ``blockByReason`` int64[NUM_ATTR_REASONS, R] per-(reason family,
        node row) block attribution, ``rtHist`` int64[NUM_RT_BUCKETS, R]
        success-RT histogram, ``totals`` int64[NUM_EVENTS, R] event
        counters. Queued leased commits are flushed first so counter
        reads are deterministic."""
        self._flush_committer()
        with self._lock:
            self._ensure_compiled()
            tele = self._state.telemetry
            sec_counts = np.asarray(self._state.sec.counts)
            block = np.asarray(tele.block_by_reason)
            hist = np.asarray(tele.rt_hist)
            totals = np.asarray(tele.totals)
            block_slot = np.asarray(tele.block_by_slot)
            stage_attr = np.asarray(tele.stage_attr)
            stage_hist = np.asarray(tele.stage_hist)
            stage_slot_bins = np.asarray(tele.stage_slot)
        # Read-side fold of the live staged second (S.telemetry_view
        # semantics, done host-side so reads never dispatch a program):
        # exact at any instant, whatever the fold cadence on device.
        return {
            "blockByReason": block + stage_attr.astype(np.int64),
            "rtHist": hist + stage_hist.astype(np.int64),
            "totals": totals + sec_counts.astype(np.int64),
            "blockBySlot": block_slot + stage_slot_bins.astype(np.int64),
        }

    def telemetry_snapshot(self) -> Dict:
        """JSON-shaped telemetry view (`telemetry` ops command parity
        with the OpenMetrics endpoint): per-resource cumulative counters,
        block attribution by reason family, and RT percentiles estimated
        from the device histogram."""
        from sentinel_tpu.core.registry import KIND_CLUSTER
        from sentinel_tpu.telemetry.attribution import (
            ATTR_REASON_NAMES, histogram_quantile)

        counts = self.telemetry_counts()
        totals = counts["totals"]
        by_reason = counts["blockByReason"]
        rt_hist = counts["rtHist"]
        active = totals.any(axis=0) | by_reason.any(axis=0)
        resources: Dict[str, Dict] = {}
        for row, meta in enumerate(self._device_metas()):
            if meta.kind != KIND_CLUSTER or row >= active.shape[0] \
                    or not active[row]:
                continue
            hist = rt_hist[:, row]
            reasons = {name: int(by_reason[ch, row])
                       for ch, name in enumerate(ATTR_REASON_NAMES)
                       if by_reason[ch, row]}
            resources[meta.resource] = {
                "passTotal": int(totals[C.MetricEvent.PASS, row]),
                "blockTotal": int(totals[C.MetricEvent.BLOCK, row]),
                "successTotal": int(totals[C.MetricEvent.SUCCESS, row]),
                "exceptionTotal": int(totals[C.MetricEvent.EXCEPTION, row]),
                "rtSumMs": int(totals[C.MetricEvent.RT, row]),
                "blockByReason": reasons,
                "rtP50Ms": round(histogram_quantile(hist, 0.50), 2),
                "rtP95Ms": round(histogram_quantile(hist, 0.95), 2),
                "rtP99Ms": round(histogram_quantile(hist, 0.99), 2),
            }
        from sentinel_tpu.telemetry.attribution import slot_bins_to_dict

        slot_out = slot_bins_to_dict(counts["blockBySlot"])
        return {
            "resources": resources,
            "counters": {
                "failOpenCount": self.fail_open_count,
                "clusterFallbackCount": self.cluster_fallback_count,
                "clusterBudgetExhaustedCount":
                    self.cluster_budget_exhausted_count,
            },
            "blockBySlot": slot_out,
            "stepTimer": self.step_timer.snapshot(),
            # Pipelined-admission health (dashboard "Pipeline" line +
            # JSON parity with the sentinel_tpu_pipeline_* gauges).
            "pipeline": self.pipeline_stats(),
            # snapshot(limit=0): the counter fields without the traces.
            "traceSampling": {
                k: v for k, v in self.traces.snapshot(limit=0).items()
                if k != "traces"
            },
            "spanSampling": {
                k: v for k, v in self.spans.snapshot(limit=0).items()
                if k != "spans"
            },
        }

    # -- flight recorder (per-second time series) --------------------------

    def _spill_flight(self, now_ms: Optional[int] = None) -> None:
        """Pull completed seconds off the device ring into the host
        history. Gathers ONLY slots newer than the last spilled stamp
        (one jitted gather, one transfer); no-op when recording is off."""
        from sentinel_tpu.telemetry.timeseries import (
            compact_second,
            second_to_dict,
        )

        now = now_ms if now_ms is not None else self.now_ms()
        fresh = []
        with self._lock:
            self._ensure_compiled()
            if self._state is not None and self._state.flight is not None:
                # Fold any completed staged second into the ring first, so
                # a read right after a second boundary sees that second.
                self._state = self._flush_jit(self._state, now)
                stamps = np.asarray(self._state.flight.stamps)
                last = self.timeseries.last_stamp_ms
                fresh = sorted(
                    (int(s), i) for i, s in enumerate(stamps.tolist())
                    if s >= 0 and s > last)
                if fresh:
                    idx_list = [i for _, i in fresh]
                    # Pad to a power-of-two ladder: a backlog of k new
                    # seconds costs at most log2(ring) distinct compiles
                    # ever (the seal_metrics discipline).
                    k = len(idx_list)
                    k_pad = 1 << (k - 1).bit_length()
                    idx = jnp.asarray(idx_list + [idx_list[0]] * (k_pad - k),
                                      jnp.int32)
                    ev, attr, hist, slot = (
                        np.asarray(x)[:k] for x in
                        self._flight_read_jit(self._state, idx))
        metas = self._device_metas()
        slots_tbl = getattr(self, "slots", None)
        for j, (stamp, _i) in enumerate(fresh):
            rec = compact_second(stamp, ev[j], attr[j], hist[j], slot[j])
            self.timeseries.append(rec)
            if slots_tbl is not None:
                # Pin the tenancy this second spilled under, so history
                # renders forever attribute a reused slot's PAST seconds
                # to the evicted occupant, never the successor.
                slots_tbl.remember_metas(stamp, metas)
            # Judgement rides the spill: each complete second feeds the
            # SLO manager's objective series + anomaly baselines (host
            # arithmetic, outside the engine lock).
            sec_dict = second_to_dict(rec, metas)
            self.slo.ingest(stamp, sec_dict["resources"])
            # Trace capture rides the same render: tees (the flight
            # recorder's trace writer, simulator/trace.py) see every
            # complete second exactly once, in stamp order. A broken tee
            # must not stall the spill (or the step stream behind it).
            for tee in list(self._flight_tees):
                try:
                    tee(sec_dict)
                except Exception:  # noqa: BLE001 — tee bugs can't stall spill
                    from sentinel_tpu.log.record_log import record_log

                    record_log.warn("flight tee %r failed; detaching", tee)
                    self.remove_flight_tee(tee)
        # Burn rules re-evaluate at the newest complete second boundary
        # on EVERY spill (even with no fresh seconds: idle decay must
        # resolve alerts without requiring new traffic).
        self.slo.evaluate(now)
        # The latency waterfall seals its staged seconds on the same
        # fold (AFTER slo.evaluate: its sentry transitions land in the
        # freshly-evaluated store). getattr for the same construction-
        # order reachability reason as adaptive below.
        waterfall = getattr(self, "waterfall", None)
        if waterfall is not None:
            waterfall.roll(now)
        # The namespace telescope folds its staged (key, count) pairs
        # into the population sketches on the same cadence (AFTER slo
        # for the same sentry-transition reason as the waterfall).
        population = getattr(self, "population", None)
        if population is not None:
            population.roll(now)
        # Slot-table rebalance rides the same cadence, AFTER the
        # telescope folded (its top-k ranking drives admit/steal) —
        # 1/s-throttled and freeze-gated inside.
        if slots_tbl is not None:
            slots_tbl.on_spill(now)
        # The adaptive loop rides the same cadence, AFTER judgement is
        # current (its freeze gate and proposal alert-gate read it).
        # Interval-gated + reentry-safe inside; getattr: _spill_flight
        # is reachable from AdaptiveLoop's own tick during construction
        # of later engine fields in exotic subclassing, and from the
        # loop's judgement refresh (which must not recurse).
        adaptive = getattr(self, "adaptive", None)
        if adaptive is not None:
            adaptive.on_spill(now)
        # Streaming-reservation hygiene rides the same cadence: leases
        # whose client vanished mid-generation evict (their remainder
        # returns as expiring credit, the abort contract), and stale
        # credit rolls off with its window.
        streams = getattr(self, "streams", None)
        if streams is not None:
            for lease in streams.evict(now):
                streams.add_credit(lease.resource, lease.remaining, now)

    def _observe_population(self, batch: EntryBatch) -> None:
        """Stage this admission batch's (row, tokens) traffic for the
        namespace telescope — a dict fold on arrays the batch already
        carries host-side, next to the existing ``traces.submit``; the
        A/B guard in tests/test_population.py pins that this adds ZERO
        device dispatches."""
        population = getattr(self, "population", None)
        if population is not None and population.enabled \
                and self.slots is None:
            # Slot mode feeds the telescope at RESOURCE grain inside
            # _slot_entry (cold entries never reach a device batch);
            # observing rows here too would double-count the hot set.
            population.observe_rows(batch.cluster_row, batch.count,
                                    self.registry.meta)

    def population_report(self, slot_budget: int = 1024,
                          now_ms: Optional[int] = None) -> Dict:
        """Admission-readiness projection for a hypothetical slot
        budget (ROADMAP item 1's sizing input): bring the telescope
        current on the fold it rides, then project hot-set hit rate,
        eviction/steal rate, and cold-tail mass from the sketches."""
        self._flush_committer()
        self._spill_flight(now_ms)
        return self.population.report(slot_budget)

    def slo_refresh(self, now_ms: Optional[int] = None) -> None:
        """Bring SLO judgement current: land leased commits, fold + spill
        any completed flight-recorder seconds (which feeds the SLO
        manager), and re-evaluate burn rules at the newest complete
        second boundary (the ``alerts``/``slo`` commands' read path)."""
        self._flush_committer()
        self._spill_flight(now_ms)

    def timeseries_view(self, resource: Optional[str] = None,
                        start_ms: Optional[int] = None,
                        end_ms: Optional[int] = None,
                        limit: Optional[int] = None,
                        offset: int = 0,
                        now_ms: Optional[int] = None) -> Dict:
        """Exact per-second telemetry series at any offset within the
        host retention (`timeseries` ops command / dashboard SSE source).

        Seconds return in CHRONOLOGICAL order; ``offset``/``limit``
        paginate newest-first (offset 0 ends at the most recent complete
        second). ``resource`` filters each second's per-resource map (a
        second with no data for it is dropped)."""
        from sentinel_tpu.telemetry.timeseries import (
            page_newest_first,
            second_to_dict,
        )

        self._flush_committer()  # leased commits land before the fold
        # ``now_ms`` drives the fold boundary: batch-API callers feeding
        # virtual clocks pass the stream's own now so the in-progress
        # second stays staged (exactness = COMPLETE seconds only).
        self._spill_flight(now_ms)
        recs = self.timeseries.query(start_ms, end_ms)
        metas = self._device_metas()
        slots_tbl = getattr(self, "slots", None)
        # Filter + paginate on the compact RECORDS, render only the
        # served page: a periodic caller (the exporter's limit=1, each
        # SSE poll) must not pay a full-history JSON render per read.
        # (In slot mode a resource's row varies per tenancy epoch, so
        # the row pre-filter only drops records where the CURRENT row
        # has no data — rendered seconds filter exactly below.)
        if resource is not None:
            row = self._device_row_of(resource)
            if slots_tbl is None:
                recs = ([r for r in recs if row in r.rows]
                        if row is not None else [])
        total = len(recs)
        recs = page_newest_first(recs, limit, offset)
        if slots_tbl is None:
            seconds = [second_to_dict(r, metas, resource) for r in recs]
        else:
            # Render each second under the tenancy it was RECORDED
            # under (the per-stamp snapshot _spill_flight pinned): a
            # reused slot's old seconds keep the evicted occupant's
            # name — the generation-leak defense for history reads.
            seconds = [
                second_to_dict(
                    r, slots_tbl.recall_metas(r.stamp_ms) or metas,
                    resource)
                for r in recs]
            if resource is not None:
                seconds = [s for s in seconds if s.get("resources")]
        return {
            "seconds": seconds,
            "total": total,
            "retainedSeconds": self.timeseries.retained(),
            "recorderSeconds": self.flight_seconds,
        }

    def explain_trace(self, resource: Optional[str] = None,
                      index: int = 0,
                      now_ms: Optional[int] = None) -> Optional[Dict]:
        """Join one sampled blocked-entry trace with the flight-recorder
        second it occurred in: what the verdict was (reason + rule slot),
        what that resource's traffic looked like THAT second (window
        occupancy, per-reason blocks), and which rules of the blocking
        family were loaded — the "why was this blocked" reconstruction,
        with no step re-run (`explain` ops command)."""
        from sentinel_tpu.datasource import converters as CV

        self.traces.drain()
        traces = self.traces.snapshot()["traces"]
        if resource is not None:
            traces = [t for t in traces if t["resource"] == resource]
        index = max(0, int(index))
        if index >= len(traces):
            return None
        tr = traces[index]
        sec_start = tr["timestamp"] - tr["timestamp"] % 1000
        view = self.timeseries_view(resource=tr["resource"],
                                    start_ms=sec_start,
                                    end_ms=sec_start + 1000,
                                    now_ms=now_ms)
        second = view["seconds"][0] if view["seconds"] else None
        fam_rules = {
            "FLOW": (self.flow_rules, CV.flow_rule_to_dict),
            "DEGRADE": (self.degrade_rules, CV.degrade_rule_to_dict),
            "AUTHORITY": (self.authority_rules, CV.authority_rule_to_dict),
            "PARAM_FLOW": (self.param_rules, CV.param_rule_to_dict),
            "SYSTEM": (self.system_rules, CV.system_rule_to_dict),
        }.get(tr["reason"])
        matched = []
        if fam_rules is not None:
            mgr, to_dict = fam_rules
            matched = [to_dict(r) for r in mgr.get_rules()
                       if getattr(r, "resource", tr["resource"])
                       == tr["resource"]]
        res_second = (second or {}).get("resources", {}).get(
            tr["resource"], {})
        return {
            "trace": tr,
            # The full second the entry fell in (None when it predates
            # retention or recording is disabled).
            "second": second,
            "occupancy": {
                "passThatSecond": res_second.get("pass", 0),
                "blockThatSecond": res_second.get("block", 0),
                "occupiedPassThatSecond": res_second.get("occupiedPass", 0),
                "windowAtTrace": tr.get("window", {}),
            },
            "verdict": {
                "reason": tr["reason"],
                "ruleSlot": tr["ruleSlot"],
                "matchedRules": matched,
            },
        }

    def why_query(self, resource: str,
                  stamp_ms: Optional[int] = None) -> Dict:
        """Forensic "why": join the flight-recorder second at
        ``stamp_ms`` with the journal records in force then — blocking
        rule + its load provenance (actor, seq, causeSeq chain), the
        rollout candidate in force, the shard map in force. The ``why``
        ops command's implementation (telemetry/journal.py)."""
        from sentinel_tpu.telemetry.journal import forensic_why

        return forensic_why(self, resource, stamp_ms)

    def row_stats(self):
        """(per-second QPS totals f32[R, E], threads int[R]) as numpy.

        Totals are normalized by the instant-window interval, so they stay
        per-second rates whatever geometry set_window_geometry picked.
        """
        self._flush_committer()
        with self._lock:
            self._ensure_compiled()
            now = self.now_ms()
            totals, threads = self._w1_read_jit(
                self._state, jnp.asarray(now, jnp.int64))
            return np.asarray(totals), np.asarray(threads)

    def tree_dict(self) -> Dict:
        """Call tree rooted at machine-root (command API ``jsonTree``/``tree``).

        Reference: ``FetchJsonTreeCommandHandler`` walking ``Constants.ROOT``.
        """
        from sentinel_tpu.core.registry import ROOT_ROW

        totals, threads = self.row_stats()
        metas = self._device_metas()

        def render(row: int) -> Dict:
            m = metas[row]
            t = totals[row]
            succ = float(t[C.MetricEvent.SUCCESS])
            return {
                "id": m.row,
                "resource": m.resource,
                "threadNum": int(threads[row]),
                "passQps": float(t[C.MetricEvent.PASS]),
                "blockQps": float(t[C.MetricEvent.BLOCK]),
                "totalQps": float(t[C.MetricEvent.PASS]) + float(t[C.MetricEvent.BLOCK]),
                "successQps": succ,
                "exceptionQps": float(t[C.MetricEvent.EXCEPTION]),
                # scale cancels in the ratio: RT and SUCCESS carry the same
                # per-second normalization
                "averageRt": float(t[C.MetricEvent.RT]) / succ if succ > 0 else 0.0,
                "children": [render(c) for c in m.children],
            }

        return render(ROOT_ROW)

    def node_snapshot(self) -> Dict[str, Dict[str, float]]:
        """Per-resource live stats (command-API ``cnode`` source)."""
        self._flush_committer()
        with self._lock:
            self._ensure_compiled()
            now = self.now_ms()
            totals, threads = self._w1_read_jit(
                self._state, jnp.asarray(now, jnp.int64))
            totals = np.asarray(totals)
            threads = np.asarray(threads)
        out = {}
        for res, row in self._device_resources().items():
            t = totals[row]
            succ = float(t[C.MetricEvent.SUCCESS])
            out[res] = {
                "passQps": float(t[C.MetricEvent.PASS]),
                "blockQps": float(t[C.MetricEvent.BLOCK]),
                "successQps": succ,
                "exceptionQps": float(t[C.MetricEvent.EXCEPTION]),
                "avgRt": float(t[C.MetricEvent.RT]) / succ if succ > 0 else 0.0,
                "curThreadNum": int(threads[row]),
            }
        return out




"""Framework adapters (reference: ``sentinel-adapter/`` — SURVEY.md §2.5):
each adapter translates a host-framework request into
``context_enter(origin) + entry(resource, IN)`` with a block-handler hook.

Python-native adapter set: a decorator (the ``@SentinelResource`` aspect
analog), WSGI and ASGI middlewares (Servlet / WebFlux analogs), the API
gateway common layer (route/API-group rules + param parsing), gRPC
server/client interceptors (``sentinel-grpc-adapter`` — import
``sentinel_tpu.adapters.grpc_adapter``, requires grpcio), an outbound
HTTP client guard (``sentinel-okhttp-adapter`` analog,
``sentinel_tpu.adapters.http_client``), asyncio coroutine guards
(``sentinel_tpu.adapters.aio``), async-stream guards — the
``sentinel-reactor-adapter`` analog (``sentinel_tpu.adapters.streams``) —
and per-framework sugar: a Flask extension
(``sentinel_tpu.adapters.flask_ext``) and a Django-style middleware
(``sentinel_tpu.adapters.django_mw``), both duck-typed so neither
framework is a dependency.
"""

from sentinel_tpu.adapters.annotation import sentinel_resource
from sentinel_tpu.adapters.asgi import SentinelASGIMiddleware
from sentinel_tpu.adapters.gateway import (
    ApiDefinition,
    ApiPredicateItem,
    GatewayApiDefinitionManager,
    GatewayFlowRule,
    GatewayParamFlowItem,
    GatewayRuleManager,
    GatewayRequest,
    api_definitions_from_json,
    api_definitions_to_json,
    gateway_entry,
    gateway_rules_from_json,
    gateway_rules_to_json,
    get_api_manager,
    get_gateway_rule_manager,
)
from sentinel_tpu.adapters.http_client import SentinelHttpClient, guarded
from sentinel_tpu.adapters.streams import guard_aiter, sentinel_stream
from sentinel_tpu.adapters.wsgi import SentinelWSGIMiddleware

__all__ = [
    "ApiDefinition", "ApiPredicateItem", "GatewayApiDefinitionManager",
    "GatewayFlowRule", "GatewayParamFlowItem", "GatewayRequest",
    "GatewayRuleManager", "SentinelASGIMiddleware", "SentinelHttpClient",
    "SentinelWSGIMiddleware", "api_definitions_from_json",
    "api_definitions_to_json", "gateway_entry", "gateway_rules_from_json",
    "gateway_rules_to_json", "get_api_manager", "get_gateway_rule_manager",
    "guard_aiter", "guarded", "sentinel_resource", "sentinel_stream",
]

"""Namespace-scoped cluster rule management (reference:
``cluster-server:flow/rule/ClusterFlowRuleManager.java`` — namespace →
property → flowId → rule; SURVEY.md §2.4).

Rules arrive as ordinary :class:`~sentinel_tpu.models.flow.FlowRule`s whose
``cluster_config`` dict carries the reference's ``ClusterFlowConfig`` fields
(``flowId``, ``thresholdType``, ``fallbackToLocalWhenFail``, ``sampleCount``,
``windowIntervalMs``). They compile to SoA tensors + a RowWindow whose
per-row bucket length encodes each rule's window geometry.
"""

from __future__ import annotations

import threading
from typing import Dict, List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from sentinel_tpu.cluster import constants as CC
from sentinel_tpu.models.flow import FlowRule
from sentinel_tpu.ops import window as W
from sentinel_tpu.utils.shapes import round_up as _round_up


def cluster_thresholds(rules) -> Dict[int, Tuple[float, int]]:
    """flowId -> (raw threshold, windowIntervalMs) from flow rules that
    carry a cluster ``flowId`` — THE single derivation of the
    degraded-quota share base (cluster/ha.py). The SEMANTICS.md
    sum-of-shares bound assumes every client computes the SAME share,
    so engine-attached clients (engine ``_cluster_threshold_map``) and
    engine-less standalone seats (:meth:`ClusterFlowRuleManager.thresholds`)
    both go through this helper."""
    out: Dict[int, Tuple[float, int]] = {}
    for r in rules:
        cc = getattr(r, "cluster_config", None) or {}
        if cc.get("flowId") is None:
            continue
        try:
            fid = int(cc["flowId"])
        except (TypeError, ValueError):
            continue
        try:
            interval = int(cc.get("windowIntervalMs",
                                  CC.DEFAULT_WINDOW_INTERVAL_MS))
        except (TypeError, ValueError):
            interval = CC.DEFAULT_WINDOW_INTERVAL_MS
        out[fid] = (float(r.count), interval)
    return out


class ClusterRuleTensors(NamedTuple):
    flow_id: jax.Array        # int64[CR]
    threshold: jax.Array      # f32[CR] raw count
    threshold_type: jax.Array  # int32[CR] AVG_LOCAL | GLOBAL
    interval_ms: jax.Array    # int64[CR]
    namespace_id: jax.Array   # int32[CR] (feeds the per-namespace conn count)

    @property
    def num_rules(self) -> int:
        return self.flow_id.shape[0]


class ClusterMetricState(NamedTuple):
    """The server-global sliding windows: one RowWindow row per flow rule."""

    win: W.RowWindow  # [CR, B, NUM_CLUSTER_EVENTS]


def make_metric_state(rt: ClusterRuleTensors, bucket_ms: np.ndarray,
                      buckets: int) -> ClusterMetricState:
    return ClusterMetricState(
        win=W.make_row_window(rt.num_rules, buckets, CC.NUM_CLUSTER_EVENTS,
                              bucket_ms))


class ClusterFlowRuleManager:
    """flowId-keyed registry across namespaces; wholesale swap per namespace."""

    def __init__(self):
        self._lock = threading.RLock()
        self._by_namespace: Dict[str, List[FlowRule]] = {}
        self._namespace_ids: Dict[str, int] = {}
        # flowId-keyed lookup maps, rebuilt on every load with the SAME
        # int-coercion as compile() — a rule loaded with flowId "123" must
        # serve request_token(123) (string/int mismatch was a lookup miss).
        self._rule_of_flow_id: Dict[int, FlowRule] = {}
        self._ns_of_flow_id: Dict[int, str] = {}
        self.version = 0
        self._listeners = []

    def namespace_id(self, namespace: str) -> int:
        with self._lock:
            nid = self._namespace_ids.get(namespace)
            if nid is None:
                nid = len(self._namespace_ids)
                self._namespace_ids[namespace] = nid
            return nid

    def namespaces(self) -> List[str]:
        with self._lock:
            return list(self._by_namespace)

    def namespace_ids(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._namespace_ids)

    def load_rules(self, namespace: str, rules: List[FlowRule]) -> None:
        """Replace one namespace's rule set (property push semantics)."""
        valid = []
        for r in rules:
            cc = r.cluster_config or {}
            try:
                int(cc.get("flowId"))
            except (TypeError, ValueError):
                continue  # missing or non-numeric flowId: drop the rule
            if r.is_valid() and r.cluster_mode:
                valid.append(r)
        with self._lock:
            self._by_namespace[namespace] = valid
            self.namespace_id(namespace)
            rule_of, ns_of = {}, {}
            for ns, rs in self._by_namespace.items():
                for r in rs:
                    fid = int((r.cluster_config or {})["flowId"])
                    rule_of[fid] = r
                    ns_of[fid] = ns
            self._rule_of_flow_id, self._ns_of_flow_id = rule_of, ns_of
            self.version += 1
            listeners = list(self._listeners)
        for fn in listeners:
            fn()

    def get_rules(self, namespace: Optional[str] = None) -> List[FlowRule]:
        with self._lock:
            if namespace is not None:
                return list(self._by_namespace.get(namespace, []))
            return [r for rs in self._by_namespace.values() for r in rs]

    def rule_by_flow_id(self, flow_id: int) -> Optional[FlowRule]:
        try:
            flow_id = int(flow_id)
        except (TypeError, ValueError):
            return None
        with self._lock:
            return self._rule_of_flow_id.get(flow_id)

    def namespace_of_flow_id(self, flow_id: int) -> Optional[str]:
        try:
            flow_id = int(flow_id)
        except (TypeError, ValueError):
            return None
        with self._lock:
            return self._ns_of_flow_id.get(flow_id)

    def add_listener(self, fn) -> None:
        with self._lock:
            self._listeners.append(fn)

    def thresholds(self) -> Dict[int, Tuple[float, int]]:
        """flowId -> (raw threshold, windowIntervalMs) for every loaded
        rule — the share base for cluster/ha.py's DegradedQuota when an
        HA participant runs from the staged server rules (engine-less
        standalone deployments)."""
        with self._lock:
            return cluster_thresholds(self._rule_of_flow_id.values())

    # -- compilation -------------------------------------------------------

    def compile(self) -> Tuple[ClusterRuleTensors, ClusterMetricState,
                               Dict[int, int], Dict[int, str]]:
        """-> (tensors, fresh metric state, flowId -> slot, flowId -> ns)."""
        with self._lock:
            items = [(ns, r) for ns, rs in self._by_namespace.items() for r in rs]
            ns_ids = dict(self._namespace_ids)
        cr = _round_up(max(len(items), 1), 8)
        flow_id = np.full(cr, -1, np.int64)
        threshold = np.zeros(cr, np.float32)
        threshold_type = np.zeros(cr, np.int32)
        interval_ms = np.zeros(cr, np.int64)
        namespace_id = np.full(cr, -1, np.int32)
        bucket_ms = np.zeros(cr, np.int64)
        slot_of: Dict[int, int] = {}
        ns_of: Dict[int, str] = {}
        max_samples = 1
        for i, (ns, r) in enumerate(items):
            cc = r.cluster_config or {}
            samples = max(1, int(cc.get("sampleCount", CC.DEFAULT_SAMPLE_COUNT)))
            interval = int(cc.get("windowIntervalMs", CC.DEFAULT_WINDOW_INTERVAL_MS))
            max_samples = max(max_samples, samples)
            flow_id[i] = int(cc["flowId"])
            threshold[i] = r.count
            threshold_type[i] = int(cc.get("thresholdType", CC.THRESHOLD_AVG_LOCAL))
            interval_ms[i] = interval
            namespace_id[i] = ns_ids[ns]
            slot_of[int(cc["flowId"])] = i
            ns_of[int(cc["flowId"])] = ns
        # The RowWindow bucket COUNT is shared (= the finest sampleCount);
        # every rule's span must still cover its own interval, so each row's
        # bucket length is ceil(interval / shared-count) — rounding UP so an
        # indivisible interval (e.g. 1000ms / 7 samples) yields a span ≥ the
        # configured interval instead of refreshing quota early. Rules asking
        # for coarser sampling just get finer buckets — same totals.
        for i, (ns, r) in enumerate(items):
            cc = r.cluster_config or {}
            interval = int(cc.get("windowIntervalMs", CC.DEFAULT_WINDOW_INTERVAL_MS))
            bucket_ms[i] = max(1, -(-interval // max_samples))
        rt = ClusterRuleTensors(
            flow_id=jnp.asarray(flow_id),
            threshold=jnp.asarray(threshold),
            threshold_type=jnp.asarray(threshold_type),
            interval_ms=jnp.asarray(interval_ms),
            namespace_id=jnp.asarray(namespace_id),
        )
        return rt, make_metric_state(rt, bucket_ms, max_samples), slot_of, ns_of

"""Deterministic fault injection for every remote touchpoint.

Named fault points live at the repo's remote seams:

* ``cluster.client.send``  — token client, before each frame write
* ``cluster.server.frame`` — token server, every reply write (bytes pass
  through :func:`mutate`, so garbage mode can corrupt the stream)
* ``datasource.read``      — every ``AbstractDataSource.load_config``
* ``heartbeat.post``       — heartbeat sender, before each POST

A :class:`FaultInjector` arms specs per point — ``error`` (raise),
``delay`` (sleep), ``garbage`` (replace bytes) — triggered by a schedule
(``after`` N calls, at most ``times`` fires) and/or a seeded
probability. Each armed point draws from its OWN ``random.Random``
stream derived deterministically from ``(seed, point)`` — arming a new
point mid-run (a chaos campaign composing schedules episode by episode)
can therefore never shift the draw sequence of already-armed points,
and un-armed points consume nothing, so a chaos run replays exactly.

Zero overhead when disabled: the module-level ``fire``/``mutate`` hooks
test one global against ``None`` and return. Production never installs
an injector; the hot-path cost is a no-arg attribute read.

Use as a context manager (installs/uninstalls the process-wide hook):

    with FaultInjector(seed=7) as inj:
        inj.arm("cluster.client.send", "error", after=2, times=3)
        ...
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Dict, Optional

FAULT_POINTS = (
    "cluster.client.send",
    "cluster.server.frame",
    "datasource.read",
    "heartbeat.post",
    # HA seams (cluster/ha.py — ISSUE 5):
    # * leader.crash — fired by the token server's batcher before each
    #   device step; an armed error hard-kills the server (listener +
    #   connections closed, NO drain checkpoint), the process-crash
    #   analog the failover chaos suite drives.
    # * halfopen — mutate seam on every server reply write; garbage=b""
    #   swallows replies while the connection stays up (a half-open
    #   socket: the client must fail over on timeout, not hang).
    # * stale.epoch — mutate seam on the epoch-TLV payload of each
    #   response; arming garbage=encode_epoch_value(old) replays a
    #   deposed leader's epoch so tests pin the client-side fence.
    "cluster.ha.leader.crash",
    "cluster.ha.halfopen",
    "cluster.ha.stale.epoch",
    # Sharded multi-leader seams (cluster/sharding.py — ISSUE 12):
    # * shard.handoff.stall — fired (delay mode) before each per-slice
    #   handoff-checkpoint publish; a stalled publish widens the
    #   recipient's warm-start margin to grants-since-the-PREVIOUS
    #   publish, which the drill asserts stays bounded.
    # * shard.map.split — fired at the top of every shard-map apply; an
    #   armed error makes that seat sit the push out, splitting the
    #   fleet across map versions (stale routers must self-heal through
    #   WRONG_SLICE walks, never double-grant through the fence).
    # * shard.donor.zombie — fired on a donor losing slices; an armed
    #   error makes it neither publish nor fence — it keeps granting
    #   the moved slices at their old epochs, and every client's
    #   per-slice fence must reject those late replies.
    "cluster.shard.handoff.stall",
    "cluster.shard.map.split",
    "cluster.shard.donor.zombie",
    # Chaos-campaign seams (ISSUE 15 — sentinel_tpu/chaos/):
    # * cluster.reactor.conn.drop — fired per connection read in the
    #   wire reactor (cluster/reactor.py) and per loopback request in
    #   the chaos mesh; an armed error kills that connection mid-stream
    #   (the peer sees a clean drop, never a half-written frame).
    # * cluster.reactor.conn.stall — same call sites; delay mode stalls
    #   the read (a wedged peer / saturated loop), error mode makes the
    #   loopback mesh record a verdict-free timeout.
    # * checkpoint.torn.write — mutate seam inside the atomic
    #   checkpoint writer (core/checkpoint.py): garbage mode TEARS the
    #   temp file before the rename publishes it (a power cut midway
    #   through the data blocks), error mode aborts before the rename
    #   (crash-before-publish; the previous file survives).
    # * journal.disk.full — fired before every durable journal append
    #   (telemetry/journal.py); an armed error is the disk-full/EIO
    #   path: the journal degrades to its in-memory tail, loudly.
    # * datasource.flap — fired per auto-refresh poll cycle
    #   (datasource/base.py) and per mesh map push (chaos/mesh.py); an
    #   armed error makes that consumer miss the push and catch up on
    #   a later cycle (distinct from datasource.read: the source is
    #   healthy, the path to it flapped).
    # * cluster.leader.clock.skew — fired by the chaos mesh when a
    #   scheduled per-leader clock skew is applied; an armed error
    #   vetoes the skew (the observability hook for skew drills).
    # * slots.evict.storm — fired at the top of every slot-table
    #   rebalance tick (core/slots.py, ABOVE the freeze gate); an armed
    #   error evicts EVERY unpinned occupant that cycle — worst-case
    #   churn for the slot_conservation invariant.
    # * slots.spill.torn — mutate seam inside the per-victim eviction
    #   spill: garbage OR error mode tears the spill record, so the
    #   victim's window state drops on the floor (counted) and it
    #   rehydrates cold — the documented bounded-loud loss.
    "cluster.reactor.conn.drop",
    "cluster.reactor.conn.stall",
    "checkpoint.torn.write",
    "journal.disk.full",
    "datasource.flap",
    "cluster.leader.clock.skew",
    "slots.evict.storm",
    "slots.spill.torn",
)


class FaultInjected(OSError):
    """Default injected error: an OSError subclass so every remote seam's
    existing except-clause treats it exactly like a real I/O failure."""

    def __init__(self, point: str):
        super().__init__(f"injected fault at {point}")
        self.point = point


@dataclass
class FaultSpec:
    mode: str                       # "error" | "delay" | "garbage"
    probability: float = 1.0        # seeded coin per triggering call
    after: int = 0                  # skip the first N calls at this point
    times: Optional[int] = None     # max fires (None = unlimited)
    delay_ms: int = 0               # delay mode
    error: Optional[BaseException] = None  # error mode override
    garbage: Optional[bytes] = None  # garbage mode payload (None = random)
    calls: int = 0
    fires: int = 0
    rng: object = None              # per-point stream, set by arm()

    def __post_init__(self):
        if self.mode not in ("error", "delay", "garbage"):
            raise ValueError(f"unknown fault mode {self.mode!r}")
        if not 0.0 <= self.probability <= 1.0:
            raise ValueError(f"probability {self.probability} not in [0, 1]")


class FaultInjector:
    def __init__(self, seed: int = 0, scope_thread: bool = False):
        self.seed = seed
        self._lock = threading.Lock()
        self._specs: Dict[str, FaultSpec] = {}
        # ``scope_thread=True`` arms the injector for the CONSTRUCTING
        # thread only: every other thread's fire()/mutate() is a no-op
        # that consumes nothing (no spec call/fire budget, no RNG draw).
        # The chaos campaign (ISSUE 15) installs with this set — its
        # whole fault surface fires on the single driver thread — so a
        # campaign run inside a live process can neither inject faults
        # into the host engine's own threads nor have them consume the
        # schedule's budget (which would break bit-identical replay).
        self._thread = threading.current_thread() if scope_thread else None

    def _foreign_thread(self) -> bool:
        return (self._thread is not None
                and threading.current_thread() is not self._thread)

    def _point_rng(self, point: str):
        """The point's own deterministic stream: seeded from
        ``(injector seed, point name)`` via a stable digest (no
        ``hash()`` — process-stable), so each point's draws are a pure
        function of the seed and ITS OWN call sequence. Arming a new
        point mid-run can never shift another point's sequence — the
        replay-stability contract chaos campaigns lean on (pinned by
        tests/test_chaos.py)."""
        import hashlib
        import random

        digest = hashlib.sha256(point.encode("utf-8")).digest()
        return random.Random(self.seed ^ int.from_bytes(digest[:8], "big"))

    # -- configuration ----------------------------------------------------

    def arm(self, point: str, mode: str, probability: float = 1.0,
            after: int = 0, times: Optional[int] = None, delay_ms: int = 0,
            error: Optional[BaseException] = None,
            garbage: Optional[bytes] = None) -> FaultSpec:
        if point not in FAULT_POINTS:
            raise ValueError(
                f"unknown fault point {point!r}; known: {FAULT_POINTS}")
        spec = FaultSpec(mode=mode, probability=probability, after=after,
                         times=times, delay_ms=delay_ms, error=error,
                         garbage=garbage, rng=self._point_rng(point))
        with self._lock:
            self._specs[point] = spec
        return spec

    def disarm(self, point: Optional[str] = None) -> None:
        with self._lock:
            if point is None:
                self._specs.clear()
            else:
                self._specs.pop(point, None)

    def fires(self, point: str) -> int:
        with self._lock:
            spec = self._specs.get(point)
            return spec.fires if spec is not None else 0

    # -- hook implementation ----------------------------------------------

    def _should_fire(self, spec: FaultSpec) -> bool:
        # Caller holds self._lock.
        spec.calls += 1
        if spec.calls <= spec.after:
            return False
        if spec.times is not None and spec.fires >= spec.times:
            return False
        if spec.probability < 1.0 and spec.rng.random() >= spec.probability:
            return False
        spec.fires += 1
        return True

    def _fire(self, point: str) -> None:
        if self._foreign_thread():
            return
        with self._lock:
            spec = self._specs.get(point)
            if spec is None or not self._should_fire(spec):
                return
            mode, delay_ms, error = spec.mode, spec.delay_ms, spec.error
        if mode == "delay":
            time.sleep(delay_ms / 1000.0)
        elif mode == "error":
            raise error if error is not None else FaultInjected(point)
        # garbage mode is a no-op at a fire-only point: there are no
        # bytes to corrupt.

    def _mutate(self, point: str, data: bytes) -> bytes:
        if self._foreign_thread():
            return data
        with self._lock:
            spec = self._specs.get(point)
            if spec is None or not self._should_fire(spec):
                return data
            mode, delay_ms, error = spec.mode, spec.delay_ms, spec.error
            if mode == "garbage":
                if spec.garbage is not None:
                    return spec.garbage
                n = max(8, len(data))
                return bytes(spec.rng.randrange(256) for _ in range(n))
        if mode == "delay":
            time.sleep(delay_ms / 1000.0)
            return data
        raise error if error is not None else FaultInjected(point)

    # -- process-wide installation ----------------------------------------

    def install(self) -> "FaultInjector":
        global _active
        if _active is not None and _active is not self:
            raise RuntimeError("another FaultInjector is already installed")
        _active = self
        return self

    def uninstall(self) -> None:
        global _active
        if _active is self:
            _active = None

    def __enter__(self) -> "FaultInjector":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()


_active: Optional[FaultInjector] = None


def fire(point: str) -> None:
    """Hook at a control-flow seam: may raise or delay per the armed spec.
    One global None-check when no injector is installed."""
    inj = _active
    if inj is not None:
        inj._fire(point)


def mutate(point: str, data: bytes) -> bytes:
    """Hook at a byte-stream seam: may corrupt/replace ``data`` (garbage
    mode), delay, or raise per the armed spec."""
    inj = _active
    if inj is None:
        return data
    return inj._mutate(point, data)

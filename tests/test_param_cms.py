"""CMS + top-k param-flow properties (BASELINE config #3 / north star).

Kernel-level tests (compile once, stream batches of hashed values) proving
the two-tier design's guarantees at 100k-key scale:

  1. **fail-closed**: no value — hot, cold, colliding — ever exceeds its
     quota within a window (CMS error is one-sided);
  2. **hot-key exactness**: a slot-owning hot key gets exact token-bucket
     admission, and a cold-key storm cannot evict it (promotion gate);
  3. **bounded cold error**: at moderate distinct-key load the CMS
     over-estimate stays small enough that innocent cold keys pass;
  4. **scale**: 100k distinct keys stream through without error growth in
     admission decisions beyond the documented one-sided direction.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sentinel_tpu.core.batch import EntryBatch, make_entry_batch_np
from sentinel_tpu.core.registry import NodeRegistry
from sentinel_tpu.models import param_flow as P
from sentinel_tpu.utils.param_hash import hash_param

NOW0 = 1_700_000_000_000


@pytest.fixture(scope="module")
def kit():
    """Compiled checker over one rule: threshold 5/s, no burst."""
    reg = NodeRegistry(64)
    row = reg.cluster_row("res")
    rules = [P.ParamFlowRule("res", param_idx=0, count=5)]
    rt = P.compile_param_rules(rules, reg, 64)
    check = jax.jit(
        lambda ps, batch, now: P.check_param_flow(
            rt, ps, batch, jnp.asarray(now, jnp.int64),
            batch.cluster_row >= 0),
    )
    return reg, row, rt, check


def _batch(row, hashes, counts=None):
    n = len(hashes)
    buf = make_entry_batch_np(n)
    buf["cluster_row"][:] = row
    buf["param_hash"][:, 0] = hashes
    buf["param_present"][:, 0] = True
    buf["count"][:] = 1 if counts is None else counts
    return EntryBatch(**{k: jnp.asarray(v) for k, v in buf.items()})


def test_no_value_over_admits_within_window(kit):
    """Six requests per value, quota 5: every value admits <= 5, whether it
    owns its slot or rides the CMS."""
    reg, row, rt, check = kit
    ps = P.make_param_state(rt.num_rules)
    rng = np.random.default_rng(3)
    hashes = rng.integers(1, 2**32, size=128, dtype=np.uint64).astype(np.uint32)
    admitted = np.zeros(128)
    for rep in range(6):  # separate batches: state carries between them
        ps_v = check(ps, _batch(row, hashes), NOW0 + rep)
        admitted += ~np.asarray(ps_v.blocked)
        ps = ps_v.state
    assert (admitted <= 5).all(), admitted.max()
    assert (admitted >= 1).all()  # nothing spuriously starved at this load


def test_hot_key_exact_and_survives_cold_storm(kit):
    """A hot key owning its slot is admitted exactly 5/window even while
    100k distinct cold keys hammer the same rule (promotion gate holds)."""
    reg, row, rt, check = kit
    ps = P.make_param_state(rt.num_rules)
    hot = np.uint32(hash_param("hot-user"))

    # Establish ownership: one quiet batch.
    ps = check(ps, _batch(row, np.full(4, hot)), NOW0).state

    hot_admits = 0
    rng = np.random.default_rng(11)
    n_cold_batches, width = 97, 1024  # ~100k distinct cold keys
    for b in range(n_cold_batches):
        cold = rng.integers(1, 2**32, size=width, dtype=np.uint64).astype(np.uint32)
        hashes = np.concatenate([[hot], cold])
        v = check(ps, _batch(row, hashes), NOW0 + 100 + b)
        ps = v.state
        hot_admits += not bool(np.asarray(v.blocked)[0])
    # quota 5/window, 4 already used at NOW0's window... the storm runs in
    # the same 1s window (NOW0+100+b all in window NOW0), so the hot key
    # gets exactly 5 - 4 = 1 more admit and NO over-admission after.
    assert hot_admits == 1
    # ownership survived: the hot key's slot still holds its hash
    slot = int(hot) % ps.key.shape[1]
    assert int(np.asarray(ps.key)[0, slot]) == int(hot)


def test_cold_keys_mostly_admitted_at_moderate_load(kit):
    """Bounded error: 4k distinct single-shot keys (sketch load ~2/cell
    before conservative update) — at least 95% must be admitted."""
    reg, row, rt, check = kit
    ps = P.make_param_state(rt.num_rules)
    rng = np.random.default_rng(7)
    admitted = total = 0
    for b in range(4):
        keys = rng.integers(1, 2**32, size=1024, dtype=np.uint64).astype(np.uint32)
        v = check(ps, _batch(row, keys), NOW0 + b)
        ps = v.state
        admitted += int((~np.asarray(v.blocked)).sum())
        total += 1024
    assert admitted / total >= 0.95, admitted / total


def test_cms_window_reset(kit):
    """A value exhausted in one window is fully available in the next —
    both the exact bucket and the sketch roll."""
    reg, row, rt, check = kit
    ps = P.make_param_state(rt.num_rules)
    key = np.uint32(hash_param("w"))
    v = check(ps, _batch(row, np.full(8, key)), NOW0)
    assert int((~np.asarray(v.blocked)).sum()) == 5
    v2 = check(v.state, _batch(row, np.full(8, key)), NOW0 + 1000)
    assert int((~np.asarray(v2.blocked)).sum()) == 5


def test_100k_distinct_keys_stream_fail_closed(kit):
    """Scale sweep: 100k distinct keys, two requests each, quota 5. The
    one-sided guarantee must hold for every key (admits <= 2 <= quota,
    never negative error), whatever the sketch collision pattern."""
    reg, row, rt, check = kit
    ps = P.make_param_state(rt.num_rules)
    rng = np.random.default_rng(23)
    over = 0
    for b in range(49):  # 49 x 1024 x 2 reqs ~= 100k keys
        keys = rng.integers(1, 2**32, size=1024, dtype=np.uint64).astype(np.uint32)
        doubled = np.repeat(keys, 2)
        v = check(ps, _batch(row, doubled), NOW0 + b)
        ps = v.state
        adm = (~np.asarray(v.blocked)).reshape(-1, 2).sum(axis=1)
        over += int((adm > 5).sum())
    assert over == 0


def test_hot_owner_survives_cold_steal_after_window_roll(kit):
    """Regression: at a window boundary the sketch DECAYS rather than
    resets, so one cold colliding request in the fresh window cannot
    steal the hot owner's slot (est 1 < owner's decayed count)."""
    reg, row, rt, check = kit
    ps = P.make_param_state(rt.num_rules)
    table = ps.key.shape[1]
    hot = np.uint32(777_001)
    cold = np.uint32(int(hot) + table)  # same slot, different value
    # Hot key uses its full quota in window 0 (owns the slot, CMS fed).
    v = check(ps, _batch(row, np.full(6, hot)), NOW0)
    ps = v.state
    assert int((~np.asarray(v.blocked)).sum()) == 5
    # First request of window 1 is the colliding cold key.
    v = check(ps, _batch(row, np.array([cold])), NOW0 + 1000)
    ps = v.state
    assert not bool(np.asarray(v.blocked)[0])  # admitted via CMS tier
    slot = int(hot) % table
    assert int(np.asarray(ps.key)[0, slot]) == int(hot)  # ownership held
    # The hot key still gets its exact fresh-window quota afterwards.
    v = check(ps, _batch(row, np.full(6, hot)), NOW0 + 1001)
    assert int((~np.asarray(v.blocked)).sum()) == 5


def test_cold_nonowner_full_quota_every_window(kit):
    """Regression: a value that never wins its slot (hot owner holds it)
    still gets its full quota each window — the admission sketch resets
    while only the promotion sketch decays."""
    reg, row, rt, check = kit
    ps = P.make_param_state(rt.num_rules)
    table = ps.key.shape[1]
    hot = np.uint32(555_001)
    cold = np.uint32(int(hot) + table)  # same slot, never promoted
    for w in range(3):
        t = NOW0 + w * 1000
        # hot key re-asserts ownership each window
        v = check(ps, _batch(row, np.full(6, hot)), t)
        ps = v.state
        assert int((~np.asarray(v.blocked)).sum()) == 5, w
        # the cold value then gets its own full per-value quota too
        v = check(ps, _batch(row, np.full(6, cold)), t + 1)
        ps = v.state
        assert int((~np.asarray(v.blocked)).sum()) == 5, w
        slot = int(hot) % table
        assert int(np.asarray(ps.key)[0, slot]) == int(hot), w

"""Redis (RESP) datasource: the first connector speaking a real external
protocol over a real socket (reference: ``sentinel-datasource-redis``'s
``RedisDataSource`` — initial GET of the rule key, then pub/sub SUBSCRIBE
for pushes; the writable side SETs the key and PUBLISHes the channel —
SURVEY.md §2.2).

Everything here is RESP2 (the stable wire dialect every Redis-compatible
server speaks): requests are arrays of bulk strings; replies are simple
strings ``+``, errors ``-``, integers ``:``, bulk strings ``$`` and
arrays ``*``. The connector owns reconnect/backoff, partial-read
reassembly, and a catch-up GET on every (re)subscribe so a push missed
during an outage is never lost.

``MiniRedisServer`` is the in-repo fake (GET/SET/DEL/PUBLISH/SUBSCRIBE/
AUTH/PING subset) used by tests and demos; point the datasource at a real
Redis and no line of the connector changes.
"""

from __future__ import annotations

import socket
import struct
import threading
import time
from typing import Dict, List, Optional, Set, Tuple

from sentinel_tpu.datasource.base import (
    AbstractDataSource,
    Converter,
    ReconnectingWatchMixin,
    T,
    WritableDataSource,
    _log_warn,
)


class RespError(Exception):
    """Server-side ``-ERR ...`` reply."""


def encode_command(*args) -> bytes:
    """RESP array-of-bulk-strings request frame."""
    out = [b"*%d\r\n" % len(args)]
    for a in args:
        raw = a if isinstance(a, bytes) else str(a).encode("utf-8")
        out.append(b"$%d\r\n%s\r\n" % (len(raw), raw))
    return b"".join(out)


class _Reader:
    """Buffered RESP reply reader: reassembles values across arbitrary
    TCP fragmentation (the protocol twin of the TLV ``FrameReader``)."""

    def __init__(self, sock: socket.socket):
        self._sock = sock
        self._buf = b""

    def _fill(self) -> None:
        data = self._sock.recv(65536)
        if not data:
            raise ConnectionError("peer closed")
        self._buf += data

    def read_line(self) -> bytes:
        while True:
            i = self._buf.find(b"\r\n")
            if i >= 0:
                line, self._buf = self._buf[:i], self._buf[i + 2:]
                return line
            self._fill()

    def read_exact(self, n: int) -> bytes:
        while len(self._buf) < n + 2:  # payload + trailing \r\n
            self._fill()
        data, self._buf = self._buf[:n], self._buf[n + 2:]
        return data

    def read_reply(self):
        """One RESP value: str | int | bytes | list | None."""
        line = self.read_line()
        kind, rest = line[:1], line[1:]
        if kind == b"+":
            return rest.decode("utf-8")
        if kind == b"-":
            raise RespError(rest.decode("utf-8"))
        if kind == b":":
            return int(rest)
        if kind == b"$":
            n = int(rest)
            return None if n < 0 else self.read_exact(n)
        if kind == b"*":
            n = int(rest)
            return None if n < 0 else [self.read_reply() for _ in range(n)]
        raise RespError(f"bad RESP type byte {kind!r}")


class RespConnection:
    """One blocking client connection (command mode or subscriber mode)."""

    def __init__(self, host: str, port: int, password: Optional[str] = None,
                 timeout_s: Optional[float] = 5.0):
        # Connect + AUTH always run under a bounded timeout, even for
        # subscriber connections that will block forever on reads later: a
        # blackholed SYN or a mute server must not park the caller where
        # close() can't interrupt it. ``timeout_s`` applies after setup.
        self.sock = socket.create_connection((host, port), timeout=5.0)
        if self.sock.getsockname() == self.sock.getpeername():
            # TCP simultaneous-open self-connect: while the server is down,
            # the kernel may hand this outgoing socket the server's own
            # port as its source port — the connect "succeeds" against
            # itself and would hang forever on the first command (and hold
            # the port hostage against the server's rebind).
            self.sock.close()
            raise ConnectionError("self-connect (server down)")
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self.reader = _Reader(self.sock)
        if password is not None:
            self.command("AUTH", password)
        self.sock.settimeout(timeout_s)

    def command(self, *args):
        self.sock.sendall(encode_command(*args))
        return self.reader.read_reply()

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass


class RedisDataSource(ReconnectingWatchMixin, AbstractDataSource[bytes, T]):
    """Initial GET + SUBSCRIBE pushes, with reconnect and catch-up.

    The subscriber connection GETs the rule key immediately before
    SUBSCRIBE on every (re)connect: an update published while the
    connection was down is recovered the moment it is back, which is the
    at-least-once delivery the reference's poll-backed sources get for
    free. Bad payloads keep the last good rules (converter errors are
    logged, never pushed)."""

    # ValueError/IndexError/UnicodeDecodeError: a corrupt or desynced
    # RESP frame from the parser — the connection is unusable but the
    # CONNECTOR must survive and reconnect.
    _watch_exceptions = (OSError, ConnectionError, RespError, ValueError,
                         IndexError, UnicodeDecodeError)
    _watch_thread_name = "sentinel-redis-subscriber"

    def __init__(self, host: str, port: int, rule_key: str, channel: str,
                 converter: Converter, password: Optional[str] = None,
                 reconnect_backoff_ms: Tuple[int, int] = (50, 2000)):
        super().__init__(converter)
        self.host, self.port = host, port
        self.rule_key, self.channel = rule_key, channel
        self.password = password
        self._active: Optional[RespConnection] = None
        self._init_watch(reconnect_backoff_ms)

    # -- ReadableDataSource ------------------------------------------------

    def read_source(self) -> Optional[bytes]:
        conn = RespConnection(self.host, self.port, self.password)
        try:
            return conn.command("GET", self.rule_key)
        finally:
            conn.close()

    def start(self) -> "RedisDataSource":
        try:
            self._push_raw(self.read_source())
        except (OSError, RespError) as ex:
            _log_warn("redis datasource initial load failed: %r", ex)
        self._start_watching()
        return self

    def close(self) -> None:
        self._join_watch()

    def _interrupt_watch(self) -> None:
        active = self._active
        if active is not None:
            # shutdown() wakes the subscriber thread out of its blocking
            # recv (a bare close would leave it parked there forever).
            try:
                active.sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass

    # -- internals ---------------------------------------------------------

    def _push_raw(self, raw: Optional[bytes]) -> None:
        if raw is None or self._stop.is_set():
            # stop guard: a straggler thread completing a connect after
            # close() must not mutate rules under a caller that believes
            # the source is shut down
            return
        try:
            value = self.converter(
                raw.decode("utf-8") if isinstance(raw, bytes) else raw)
        except Exception as ex:  # keep last good rules
            _log_warn("redis datasource bad payload: %r", ex)
            return
        if value is not None:
            self._property.update_value(value)

    def _watch_round(self) -> None:
        """One connect → subscribe → catch-up → read-until-error cycle."""
        conn = None
        try:
            conn = RespConnection(self.host, self.port, self.password,
                                  timeout_s=None)
            self._active = conn
            sub = conn.command("SUBSCRIBE", self.channel)
            if not (isinstance(sub, list) and sub
                    and sub[0] == b"subscribe"):
                raise RespError(f"unexpected SUBSCRIBE reply {sub!r}")
            # catch-up AFTER subscribe (on a command connection — a
            # subscribed conn can't GET): an update missed while down
            # is recovered here, and one racing this instant arrives
            # as a message too. GET-then-subscribe would have a lossy
            # gap between the two; this order has none.
            self._push_raw(self.read_source())
            self._healthy()
            while not self._stop.is_set():
                msg = conn.reader.read_reply()
                if (isinstance(msg, list) and len(msg) == 3
                        and msg[0] == b"message"):
                    self._push_raw(msg[2])
        finally:
            self._active = None
            if conn is not None:
                conn.close()


class RedisWritableDataSource(WritableDataSource[T]):
    """SET the rule key + PUBLISH the channel (the reference publisher's
    two-step, so poll-style AND push-style readers both see the write)."""

    def __init__(self, host: str, port: int, rule_key: str, channel: str,
                 encoder: Converter, password: Optional[str] = None):
        self.host, self.port = host, port
        self.rule_key, self.channel = rule_key, channel
        self.encoder = encoder
        self.password = password

    def write(self, value: T) -> None:
        raw = self.encoder(value)
        conn = RespConnection(self.host, self.port, self.password)
        try:
            conn.command("SET", self.rule_key, raw)
            conn.command("PUBLISH", self.channel, raw)
        finally:
            conn.close()


# -- in-repo fake server ------------------------------------------------------


class MiniRedisServer:
    """RESP2 subset server (GET/SET/DEL/PUBLISH/SUBSCRIBE/UNSUBSCRIBE/
    AUTH/PING) for tests and demos. ``stop()`` + ``start()`` rebinds the
    SAME port, so reconnect paths are testable; the KV survives a restart
    (a real Redis with persistence would too), unless ``clear()``."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 password: Optional[str] = None):
        self.host = host
        self.port = port
        self.password = password
        self._kv: Dict[bytes, bytes] = {}
        self._lock = threading.Lock()
        # channel -> set of (socket, send-lock) subscriber entries
        self._subs: Dict[bytes, Set] = {}
        self._listener: Optional[socket.socket] = None
        self._conns: List[socket.socket] = []
        self._threads: List[threading.Thread] = []
        self._stopping = threading.Event()

    def start(self) -> "MiniRedisServer":
        self._stopping.clear()
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        deadline = time.time() + 3.0
        while True:
            try:
                self._listener.bind((self.host, self.port))
                break
            except OSError:
                # A reconnecting client can transiently hold our port as
                # its ephemeral source port (see RespConnection's
                # self-connect guard); it releases within its backoff.
                if time.time() >= deadline:
                    raise
                time.sleep(0.05)
        self.port = self._listener.getsockname()[1]  # pin for restarts
        self._listener.listen(16)
        t = threading.Thread(target=self._accept_loop,
                             name="mini-redis-accept", daemon=True)
        t.start()
        self._threads.append(t)
        return self

    def stop(self) -> None:
        """Close the listener and every live connection (simulates a
        server crash for reconnect tests); KV state is retained.

        Socket discipline (all three measured necessary for an instant
        same-port restart on Linux): ``shutdown()`` before ``close()`` —
        a plain close never wakes a thread blocked in accept()/recv(),
        whose in-syscall reference keeps the fd (and the LISTEN) alive
        forever; SO_LINGER(0) so accepted sockets RST instead of parking
        the port in TIME_WAIT; each conn's own serve thread does the
        final close()."""
        self._stopping.set()
        if self._listener is not None:
            try:
                self._listener.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                self._listener.close()
            except OSError:
                pass
            self._listener = None
        with self._lock:
            conns, self._conns = self._conns, []
            self._subs.clear()
        for c in conns:
            try:
                c.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                             struct.pack("ii", 1, 0))
            except OSError:
                pass
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
        for t in self._threads:
            t.join(timeout=1.0)
        self._threads = []

    def clear(self) -> None:
        with self._lock:
            self._kv.clear()

    # -- plumbing ----------------------------------------------------------

    def _accept_loop(self) -> None:
        listener = self._listener
        while not self._stopping.is_set():
            try:
                conn, _ = listener.accept()
            except OSError:
                return
            with self._lock:
                self._conns.append(conn)
            t = threading.Thread(target=self._serve, args=(conn,),
                                 name="mini-redis-conn", daemon=True)
            t.start()
            self._threads.append(t)

    def _serve(self, conn: socket.socket) -> None:
        reader = _Reader(conn)
        send_lock = threading.Lock()
        authed = self.password is None
        subscribed: Set[bytes] = set()

        def reply(data: bytes) -> None:
            with send_lock:
                conn.sendall(data)

        try:
            while not self._stopping.is_set():
                req = reader.read_reply()
                if not isinstance(req, list) or not req:
                    reply(b"-ERR protocol error\r\n")
                    continue
                cmd = bytes(req[0]).upper()
                args = req[1:]
                if cmd == b"AUTH":
                    if (self.password is not None and len(args) == 1
                            and args[0] == self.password.encode()):
                        authed = True
                        reply(b"+OK\r\n")
                    else:
                        reply(b"-ERR invalid password\r\n")
                    continue
                if not authed:
                    reply(b"-NOAUTH Authentication required.\r\n")
                    continue
                if cmd == b"PING":
                    reply(b"+PONG\r\n")
                elif cmd == b"GET" and len(args) == 1:
                    with self._lock:
                        v = self._kv.get(args[0])
                    reply(b"$-1\r\n" if v is None
                          else b"$%d\r\n%s\r\n" % (len(v), v))
                elif cmd == b"SET" and len(args) == 2:
                    with self._lock:
                        self._kv[args[0]] = args[1]
                    reply(b"+OK\r\n")
                elif cmd == b"DEL":
                    with self._lock:
                        n = sum(1 for k in args if self._kv.pop(k, None)
                                is not None)
                    reply(b":%d\r\n" % n)
                elif cmd == b"PUBLISH" and len(args) == 2:
                    reply(b":%d\r\n" % self._publish(args[0], args[1]))
                elif cmd == b"SUBSCRIBE" and args:
                    for ch in args:
                        subscribed.add(ch)
                        # Registration and ack under ONE send_lock hold:
                        # a racing PUBLISH (which sends under send_lock
                        # but never holds self._lock while sending) can
                        # otherwise deliver its message frame BEFORE the
                        # +subscribe ack, which clients read as a bogus
                        # SUBSCRIBE reply.
                        with send_lock:
                            with self._lock:
                                self._subs.setdefault(ch, set()).add(
                                    (conn, send_lock))
                            conn.sendall(b"*3\r\n$9\r\nsubscribe\r\n"
                                         b"$%d\r\n%s\r\n:%d\r\n"
                                         % (len(ch), ch, len(subscribed)))
                elif cmd == b"UNSUBSCRIBE":
                    for ch in (args or list(subscribed)):
                        subscribed.discard(ch)
                        with self._lock:
                            self._subs.get(ch, set()).discard(
                                (conn, send_lock))
                        reply(b"*3\r\n$11\r\nunsubscribe\r\n"
                              b"$%d\r\n%s\r\n:%d\r\n"
                              % (len(ch), ch, len(subscribed)))
                else:
                    reply(b"-ERR unknown command %s\r\n"
                          % cmd.decode("ascii", "replace").encode())
        except (ConnectionError, OSError):
            pass
        finally:
            with self._lock:
                for ch in subscribed:
                    self._subs.get(ch, set()).discard((conn, send_lock))
                if conn in self._conns:
                    self._conns.remove(conn)
            try:
                conn.close()
            except OSError:
                pass

    def _publish(self, channel: bytes, payload: bytes) -> int:
        with self._lock:
            targets = list(self._subs.get(channel, ()))
        delivered = 0
        frame = (b"*3\r\n$7\r\nmessage\r\n$%d\r\n%s\r\n$%d\r\n%s\r\n"
                 % (len(channel), channel, len(payload), payload))
        for sock, lock in targets:
            try:
                with lock:
                    sock.sendall(frame)
                delivered += 1
            except OSError:
                pass
        return delivered

"""Block-reason attribution + RT histogram geometry (device/host shared).

The fused step (``ops/step.py``) decides admit/block for every entry but
the window tensors record only aggregate PASS/BLOCK per node row — an
operator seeing a block-rate spike cannot tell WHICH family (or which
rule of that family) is rejecting traffic. This module fixes the
vocabulary both sides share:

* **Reason channels**: the cumulative per-(resource, reason) counter
  tensor carries one channel per blockable family, indexed by
  :data:`ATTR_REASON_VALUES` order. The step commits blocked lanes with
  ONE in-place single-column scatter into an int32 staging tensor (the
  SecondAccum trick: the wide int64 cumulative fold happens once per
  second, not per step — riding the shared bincount as 6 extra value
  columns was measured at ~13% of the bench step; the scatter is noise).
* **Reason codes**: the per-entry detail is ``(family, first-blocking
  rule slot)`` packed into one int (``encode_reason_code``) — the slot is
  the index into the resource's per-family rule list in load order,
  exactly the position the sequential slot chain would have thrown from.
* **RT buckets**: log2-spaced response-time histogram edges. The exit
  step buckets each success completion on device and commits one column
  per bucket, replacing avg-only RT readings with real percentiles
  (``histogram_quantile``).
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from sentinel_tpu.core import constants as C

# Families a device verdict can attribute a block to, in channel order.
# WAIT is not a block (pass-after-sleep) and PASS is not attributed.
ATTR_REASON_VALUES: Tuple[int, ...] = (
    int(C.BlockReason.FLOW),
    int(C.BlockReason.DEGRADE),
    int(C.BlockReason.SYSTEM),
    int(C.BlockReason.AUTHORITY),
    int(C.BlockReason.PARAM_FLOW),
    int(C.BlockReason.CUSTOM),
)
ATTR_REASON_NAMES: Tuple[str, ...] = tuple(
    C.BlockReason(v).name for v in ATTR_REASON_VALUES)
NUM_ATTR_REASONS = len(ATTR_REASON_VALUES)

# Channel index for a BlockReason value (-1 for PASS/WAIT).
_CHANNEL_OF = {v: i for i, v in enumerate(ATTR_REASON_VALUES)}


def reason_channel(reason: int) -> int:
    return _CHANNEL_OF.get(int(reason), -1)


# Device-side lookup: channel per BlockReason value (-1 = unattributed).
# numpy, created at import — folds as a constant per trace (never a
# cached tracer).
REASON_CHANNEL_TABLE = np.full((max(int(v) for v in C.BlockReason) + 1,),
                               -1, np.int32)
for _v, _ch in _CHANNEL_OF.items():
    REASON_CHANNEL_TABLE[_v] = _ch


# Rule-slot field width in the packed reason code. MAX_SLOT_CODE bounds
# the encodable slot index; real slot counts are the engine's per-family
# ratchet (single digits in practice).
_SLOT_BITS = 8
MAX_SLOT_CODE = (1 << _SLOT_BITS) - 2  # one value reserved for "unknown"


def encode_reason_code(reason: int, slot: int) -> int:
    """``family × first-blocking-slot`` packed as one int.

    ``slot`` is the 0-based index into the resource's rule list for the
    blocking family; -1 (unknown — e.g. a remote token-server verdict
    carries no local rule identity) encodes as the reserved top value.
    ``reason`` 0 (PASS) always encodes to 0.
    """
    if reason == 0:
        return 0
    s = MAX_SLOT_CODE + 1 if slot < 0 else min(int(slot), MAX_SLOT_CODE)
    return (int(reason) << _SLOT_BITS) | s


def decode_reason_code(code: int) -> Tuple[int, int]:
    """Inverse of :func:`encode_reason_code` -> ``(reason, slot)``."""
    if code == 0:
        return 0, -1
    slot = code & ((1 << _SLOT_BITS) - 1)
    return code >> _SLOT_BITS, (-1 if slot > MAX_SLOT_CODE else slot)


# ---------------------------------------------------------------------------
# Rule-slot bins for the flight recorder's per-(reason, slot) series
# (telemetry/timeseries.py): slots 0..MAX individually, one bin for the
# long tail, one for "unknown" (-1: remote token-server verdicts, system
# rules' global set). Real per-resource slot counts are single digits
# (the engine's per-family ratchet), so 8 exact bins cover practice.
# ---------------------------------------------------------------------------

SLOT_BIN_MAX_EXACT = 8                    # bins 0..7 are exact slot indices
SLOT_BIN_OVERFLOW = SLOT_BIN_MAX_EXACT    # slot >= 8
SLOT_BIN_UNKNOWN = SLOT_BIN_MAX_EXACT + 1  # slot -1 (remote / unattributed)
NUM_SLOT_BINS = SLOT_BIN_MAX_EXACT + 2

SLOT_BIN_LABELS: Tuple[str, ...] = tuple(
    [str(i) for i in range(SLOT_BIN_MAX_EXACT)] + ["8+", "unknown"])


def slot_bin_index(slot: jax.Array) -> jax.Array:
    """int32[N] slot bin per rule-slot value (device-side)."""
    binned = jnp.minimum(slot, SLOT_BIN_OVERFLOW)
    return jnp.where(slot < 0, SLOT_BIN_UNKNOWN, binned).astype(jnp.int32)


def slot_bins_to_dict(arr) -> dict:
    """[NUM_ATTR_REASONS, NUM_SLOT_BINS] counts -> {reason: {label:
    count}} with zero bins and empty reasons skipped — the ONE rendering
    of the (reason, slot) split every JSON surface shares (`telemetry`
    snapshot, `timeseries` seconds, SSE, `explain`)."""
    out = {}
    for ch, reason in enumerate(ATTR_REASON_NAMES):
        bins = {SLOT_BIN_LABELS[b]: int(arr[ch, b])
                for b in range(min(arr.shape[1], NUM_SLOT_BINS))
                if arr[ch, b]}
        if bins:
            out[reason] = bins
    return out


# ---------------------------------------------------------------------------
# RT histogram geometry: log2 buckets 1ms..4096ms + overflow. The top edge
# clears DEFAULT_MAX_RT_MS (4900 is clamped on commit, landing in +Inf
# only for the raw >4096 tail), and 14 buckets keep the per-step commit at
# 14 extra bincount columns — shared-operand, one fused scatter.
# ---------------------------------------------------------------------------

RT_BUCKET_EDGES_MS: Tuple[int, ...] = (
    1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024, 2048, 4096)
NUM_RT_BUCKETS = len(RT_BUCKET_EDGES_MS) + 1  # + overflow (+Inf)

# numpy, NOT jnp: created at import (never inside a trace, where a cached
# jnp array would be a leaked tracer) and folded as a constant per trace.
_EDGES = np.asarray(RT_BUCKET_EDGES_MS, np.int32)


def rt_bucket_index(rt_ms: jax.Array) -> jax.Array:
    """int32[N] histogram bucket per response time (device-side).

    Bucket b counts ``rt <= edge_b`` (Prometheus ``le`` semantics per
    bucket, cumulated at export time); the last bucket is the +Inf
    overflow.
    """
    return jnp.sum(rt_ms[:, None] > _EDGES[None, :], axis=1).astype(jnp.int32)


# ---------------------------------------------------------------------------
# Waterfall geometry (ISSUE 18): the wire-to-device stage histograms need
# sub-millisecond resolution (a reactor parse runs tens of microseconds)
# while sharing the log2 ladder and +Inf overflow convention above. One
# family, one geometry: pipeline queue/device waits and every wire stage
# bucket into THIS ladder, 2^-6 ms (15.6us) .. 2^12 ms (4096ms).
# ---------------------------------------------------------------------------

WF_BUCKET_EDGES_MS: Tuple[float, ...] = tuple(
    float(2.0 ** k) for k in range(-6, 13))
NUM_WF_BUCKETS = len(WF_BUCKET_EDGES_MS) + 1  # + overflow (+Inf)


def bucket_index_of(value_ms: float,
                    edges: Sequence[float] = WF_BUCKET_EDGES_MS) -> int:
    """Host-side bucket index for one observation (``le`` semantics:
    bucket b holds ``value <= edge_b``; past the last edge -> overflow)."""
    for b, edge in enumerate(edges):
        if value_ms <= edge:
            return b
    return len(edges)


def histogram_quantile_edges(counts: Sequence[float], q: float,
                             edges: Sequence[float]) -> float:
    """Estimate the q-quantile (0..1) from per-bucket counts over an
    arbitrary edge ladder (``counts`` = len(edges) buckets + overflow).

    Linear interpolation within the winning bucket (Prometheus
    ``histogram_quantile`` convention); the overflow bucket reports its
    lower edge. Returns 0.0 on an empty histogram.
    """
    total = float(sum(counts))
    if total <= 0:
        return 0.0
    target = q * total
    cum = 0.0
    for b, cnt in enumerate(counts):
        prev = cum
        cum += float(cnt)
        if cum >= target and cnt > 0:
            if b >= len(edges):  # overflow: no upper edge
                return float(edges[-1])
            lo = 0.0 if b == 0 else float(edges[b - 1])
            hi = float(edges[b])
            return lo + (hi - lo) * (target - prev) / float(cnt)
    return float(edges[-1])


def histogram_quantile(counts: Sequence[float], q: float) -> float:
    """Estimate the q-quantile (0..1) from per-bucket counts.

    ``counts`` is indexed like :data:`RT_BUCKET_EDGES_MS` plus the
    overflow bucket (the device RT geometry). Delegates to
    :func:`histogram_quantile_edges`.
    """
    return histogram_quantile_edges(counts, q, RT_BUCKET_EDGES_MS)
